"""Gradient/parameter synchronization for data-parallel SGD.

Rebuild of ``torchmpi.nn`` (SURVEY.md §3 C10, §4.3, reconstructed — reference
mount empty): ``synchronizeParameters(net)`` broadcast the parameters from
rank 0 at init; ``synchronizeGradients(net)`` allreduced gradParams after each
backward; an async variant overlapped per-layer allreduces with backprop.

TPU-native mapping:

- *Parameter sync* is a sharding statement: replicating the pytree over the
  mesh (``NamedSharding(mesh, P())``) makes every device hold rank-0's copy —
  the broadcast happens in the transfer.  An explicit in-axis broadcast is
  also provided for divergent-state repair (the reference's re-sync use case).
- *Gradient sync* is selector-routed ``allreduce_in_axis`` inside the jitted
  train step, so the hierarchical / custom backends apply to the hot path.
- *The async per-layer overlap* becomes **bucketing**: gradients are flattened
  into K buckets, each allreduced separately inside jit — XLA's latency-hiding
  scheduler overlaps bucket k's collective with bucket k+1's computation,
  playing the role of the reference's per-module hooks firing during backward.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import collectives, fusion, planner, runtime

PyTree = Any
AxisNames = Union[str, Tuple[str, ...]]


def _default_mesh(mesh: Optional[Mesh]) -> Mesh:
    return mesh if mesh is not None else runtime.current_mesh()


def _all_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(mesh.axis_names)


# ---------------------------------------------------------------------------
# Parameter synchronization (reference: mpinn.synchronizeParameters)
# ---------------------------------------------------------------------------


def synchronize_parameters(params: PyTree, *, mesh: Optional[Mesh] = None,
                           copy: bool = True) -> PyTree:
    """Replicate a parameter pytree across every device of the mesh.

    The reference broadcast ``net:parameters()`` from rank 0; here the
    replicating ``device_put`` *is* that broadcast (source: the controller's
    copy).  Returns the same values, now resident and replicated on the mesh.

    ``copy=True`` (default) breaks buffer aliasing with the input: a
    device_put of an on-device array can return an aliased buffer, and the
    usual next step donates the result into a train step — which would
    silently delete the caller's template.  This is an init-time op; the
    extra host round-trip is irrelevant.
    """
    m = _default_mesh(mesh)
    repl = NamedSharding(m, P())

    def put(a):
        if copy and isinstance(a, jax.Array):
            if a.is_fully_addressable:
                a = np.asarray(a)
            else:
                # Multi-host global array: host readback is impossible;
                # a device-side copy (fresh buffers, no donation) breaks
                # the aliasing just as well.
                a = jnp.copy(a)
        return jax.device_put(a, repl)

    return jax.tree.map(put, params)


def resynchronize_parameters_in_axis(params: PyTree, axis_names: AxisNames,
                                     *, root: int = 0,
                                     backend: Optional[str] = None) -> PyTree:
    """In-axis broadcast of params from ``root`` — for use inside shard_map
    when per-device state may have diverged (async PS training, debugging)."""
    return collectives.broadcast_in_axis(params, axis_names, root=root,
                                         backend=backend)


# ---------------------------------------------------------------------------
# Gradient synchronization (reference: mpinn.synchronizeGradients)
# ---------------------------------------------------------------------------


# The flatten/bucket/shard machinery is the fusion layer's FusedSpec —
# ONE definition shared by the fused in-axis collectives, the bucketed
# allreduce here, and ZeRO's shard layout (parallel/zero.py).  The old
# names stay importable: FlatSpec(tree, n_shards) is the same contract
# (single-dtype trees lay out byte-identically; mixed-dtype trees are
# now group-major so the wire never promotes).
FlatSpec = fusion.FusedSpec
flatten_tree = fusion.flatten_tree
unflatten_tree = fusion.unflatten_tree


def _bucketed_allreduce(grads: PyTree, axes: Tuple[str, ...], *, op: str,
                        n_buckets: int, backend: Optional[str],
                        barrier: bool = False) -> PyTree:
    """Per dtype group: concat -> ~K buckets -> one allreduce each ->
    unflatten (buckets distribute across groups by byte share; a
    single-dtype tree gets exactly K, the pre-fusion contract).

    The analog of the reference's async per-layer hooks (SURVEY §4.3): K
    independent collectives inside one jit give XLA the freedom to overlap
    them with surrounding compute.  Unlike the old promoted concat, each
    group reduces in its native dtype — a mixed fp32/bf16 tree keeps
    bf16 leaves bf16 on the wire.

    ``barrier=True`` chains each bucket's input on the previous bucket's
    output (across dtype groups too) through ``lax.optimization_barrier``,
    which keeps the K all-reduces DISTINCT through XLA's all-reduce
    combiner (measured: below the combine threshold the combiner
    otherwise merges every bucket into one collective —
    docs/artifacts/overlap_summary.md) and issues them in order, so the
    latency-hiding scheduler can overlap bucket i's downstream use with
    bucket i+1's collective.  The cost is serialization of the
    collectives themselves; leave it off when one fused all-reduce is
    fastest (small models).

    The bucketing spec and per-bucket backend choices are planned once
    per gradient-tree structure and replayed across step builds
    (:func:`torchmpi_tpu.planner.plan_gradsync`).
    """
    if not jax.tree.leaves(grads):
        return grads
    plan = planner.plan_gradsync(grads, axes, op=op, n_buckets=n_buckets,
                                 backend=backend, barrier=barrier)
    if plan is not None:
        return plan.replay(grads)
    spec = fusion.FusedSpec(grads, n_buckets=n_buckets)
    return fusion.fuse_tree("allreduce", grads, axes, backend=backend,
                            barrier=barrier, spec=spec, op=op)


def synchronize_gradients(grads: PyTree, axis_names: Optional[AxisNames] = None,
                          *, op: Optional[str] = None,
                          n_buckets: Optional[int] = None,
                          backend: Optional[str] = None,
                          compress: Optional[str] = None,
                          barrier: Optional[bool] = None) -> PyTree:
    """Allreduce a gradient pytree across the data-parallel axes.

    For use inside a shard_map'd/jitted train step (the hot path).  Defaults:
    axes = every axis of the current world mesh; ``op`` = mean when
    ``config.gradsync_average`` (the reference allreduce-summed then divided
    by ``mpi.size()``); ``n_buckets`` from config.

    ``compress="bf16"`` halves bytes on the wire by reducing in bfloat16 and
    casting back — the lever that matters when the allreduce is DCN-bound
    (multi-slice scaling); gradients tolerate it in practice.  Config
    default: ``gradsync_compress``.

    ``barrier`` (config default ``gradsync_barrier``) keeps bucketed
    all-reduces distinct through XLA's combiner via optimization
    barriers — see :func:`_bucketed_allreduce`.

    With ``n_buckets <= 1`` the tree rides the fused in-axis allreduce
    (``config.fuse_max_bytes``): dtype-grouped coalescing, O(dtypes x
    buckets) launches instead of one per leaf, bit-identical results.
    """
    if axis_names is None:
        axis_names = _all_axes(runtime.current_mesh())
    axes = (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)
    cfg = runtime.config() if runtime.is_initialized() else None
    if op is None:
        op = "mean" if (cfg is None or cfg.gradsync_average) else "sum"
    if n_buckets is None:
        n_buckets = cfg.gradsync_buckets if cfg is not None else 1
    if compress is None and cfg is not None:
        compress = cfg.gradsync_compress
    if barrier is None:
        barrier = cfg.gradsync_barrier if cfg is not None else False
    if cfg is not None and cfg.obs != "off":
        from .. import obs

        obs.record_gradsync(n_buckets, op, compress == "bf16")
    orig_dtypes = None
    if compress == "bf16":
        orig_dtypes = jax.tree.map(lambda g: g.dtype, grads)
        grads = jax.tree.map(lambda g: g.astype(jnp.bfloat16), grads)
    elif compress not in (None, "none"):
        raise ValueError(f"unknown gradient compression {compress!r}")
    if n_buckets <= 1:
        out = collectives.allreduce_in_axis(grads, axes, op=op,
                                            backend=backend)
    else:
        out = _bucketed_allreduce(grads, axes, op=op, n_buckets=n_buckets,
                                  backend=backend, barrier=barrier)
    if orig_dtypes is not None:
        out = jax.tree.map(lambda g, d: g.astype(d), out, orig_dtypes)
    return out


# ---------------------------------------------------------------------------
# Backprop-overlapped gradient sync (docs/OVERLAP.md).  The reference's
# async per-layer hooks fired an allreduce per module as its gradParams
# arrived during backward; the TPU-native equivalent wraps each gradient
# BUCKET's parameters in a custom_vjp whose backward rule IS the
# bucket's allreduce — the collective then sits in the backward graph at
# exactly the point where that bucket's cotangents are complete, and the
# latency-hiding scheduler hides it under the remaining backward
# compute.  An optimization-barrier token chain (the gradsync_barrier
# machinery, threaded through the custom_vjp rules) keeps the buckets
# distinct through XLA's all-reduce combiner and issues them in
# materialization order.
# ---------------------------------------------------------------------------


def overlap_bucket_bytes(mesh: Optional[Mesh] = None) -> int:
    """Byte bound for one overlap bucket: ``config.
    gradsync_overlap_bytes`` when set, else the tuning-plan-aligned
    bound (:func:`torchmpi_tpu.tuning.plan_bucket_bytes`) — the largest
    measured allreduce size bucket for this mesh when a plan is active,
    else ``fuse_max_bytes`` rounded down to a plan bucket edge.  Sizing
    from the plan's log2 buckets (instead of a fixed ``n_buckets``)
    keys every fired bucket to a collective size somebody measured."""
    cfg = runtime.effective_config()
    if cfg.gradsync_overlap_bytes > 0:
        return int(cfg.gradsync_overlap_bytes)
    from .. import tuning

    m = _default_mesh(mesh)
    return tuning.plan_bucket_bytes("allreduce", m,
                                    cfg.fuse_max_bytes or 32 * 1024 * 1024)


def assign_overlap_buckets(leaves: Sequence, max_bytes: int
                           ) -> List[List[int]]:
    """Reverse-parameter-order bucket assignment: walk the flattened
    tree's leaves LAST to FIRST — the order their cotangents
    materialize during backprop — starting a new bucket when the byte
    bound fills or the dtype changes (buckets stay dtype-pure, the
    fusion discipline: a mixed fp32/bf16 tree never promotes on the
    wire).  Returns buckets of leaf indices in FIRING order: bucket 0
    (the deepest layers) launches first."""
    max_bytes = max(1, int(max_bytes))
    buckets: List[List[int]] = []
    acc = 0
    cur_dt = None
    for i in range(len(leaves) - 1, -1, -1):
        leaf = leaves[i]
        b = int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        if (not buckets or np.dtype(leaf.dtype) != cur_dt
                or acc + b > max_bytes):
            buckets.append([])
            acc = 0
            cur_dt = np.dtype(leaf.dtype)
        buckets[-1].append(i)
        acc += b
    return buckets


def _make_bucket_sync(idx: int, total: int, axes: Tuple[str, ...],
                      op: str, backend: Optional[str],
                      compress: Optional[str],
                      impl: Optional[Callable] = None):
    """One bucket's sync op: identity in forward, THE bucket's
    allreduce in backward.  ``token`` threads the optimization-barrier
    chain across buckets: the backward rule barriers its allreduce
    input on the incoming token (the previous-fired bucket's launch)
    and derives its outgoing token from the allreduce result — so the
    collectives stay distinct through the combiner and issue in firing
    order, each eligible the moment its cotangents exist.  ``impl`` is
    the planner's pre-picked allreduce implementation for this bucket
    (None falls back to a per-trace selector pick)."""

    @jax.custom_vjp
    def sync(xs, token):
        return xs, token

    def fwd(xs, token):
        return (xs, token), None

    def bwd(_, cts):
        g, tok = cts
        shapes = [x.shape for x in g]
        sizes = [int(np.prod(s)) for s in shapes]
        obs_on = runtime.effective_config().obs != "off"
        if obs_on:
            from .. import obs

            # Runtime evidence, not trace-time: the callback fires when
            # this bucket's cotangents materialize on each device — the
            # flight-ring ordering of grads/launch events across
            # buckets is the CPU-sim-checkable overlap invariant.
            jax.debug.callback(
                lambda *_a, _o=obs, _k=idx, _t=total:
                _o.record_overlap("grads", _k, _t),
                g[0].reshape(-1)[:1])
        flat = (g[0].reshape(-1) if len(g) == 1
                else jnp.concatenate([x.reshape(-1) for x in g]))
        orig_dtype = flat.dtype
        if compress == "bf16":
            flat = flat.astype(jnp.bfloat16)
        flat, _ = lax.optimization_barrier((flat, tok))
        if obs_on:
            from .. import obs

            jax.debug.callback(
                lambda *_a, _o=obs, _k=idx, _t=total:
                _o.record_overlap("launch", _k, _t),
                flat[:1])
        bucket_impl = impl
        if bucket_impl is None:
            bucket_impl = collectives._pick(  # noqa: SLF001 — shared route
                "allreduce", flat, backend, axes)
        red = bucket_impl(flat, axes, op=op)
        if compress == "bf16":
            red = red.astype(orig_dtype)
        anchor = red[0] if sum(sizes) else tok
        tok_out, _ = lax.optimization_barrier((tok, anchor))
        out, off = [], 0
        for s, sz in zip(shapes, sizes):
            out.append(red[off:off + sz].reshape(s))
            off += sz
        return (tuple(out), tok_out)

    sync.defvjp(fwd, bwd)
    return sync


def make_overlapped_grad_fn(loss_fn: Callable, params_template: PyTree,
                            axis_names: Optional[AxisNames] = None, *,
                            mesh: Optional[Mesh] = None,
                            op: Optional[str] = None,
                            backend: Optional[str] = None,
                            compress: Optional[str] = None,
                            has_aux: bool = False,
                            max_bytes: Optional[int] = None) -> Callable:
    """Build a ``value_and_grad`` whose gradients come back ALREADY
    allreduced, with each bucket's collective fired inside the backward
    pass as its cotangents materialize (the DDP overlap schedule; the
    reference's async per-layer hooks).

    For use INSIDE a shard_map'd/jitted train step, where
    ``synchronize_gradients`` would otherwise run after the full
    backward::

        vag = gradsync.make_overlapped_grad_fn(loss_fn, params, axes)
        loss, grads = vag(params, batch)      # grads are synced

    ``params_template`` supplies leaf shapes/dtypes for the bucket
    assignment — the traced ``params`` themselves work (the recipes
    step builders do exactly that), as does an ``eval_shape`` tree.
    Buckets are assigned in reverse parameter order (:func:
    `assign_overlap_buckets`) and sized from the tuning-plan size
    buckets (:func:`overlap_bucket_bytes`) unless ``max_bytes`` is
    given.  Defaults: ``op`` from ``config.gradsync_average``,
    ``compress`` from ``config.gradsync_compress`` — exactly
    :func:`synchronize_gradients`'s, and the results are bit-identical
    to it (test-asserted; the fused reductions are elementwise over
    the same cross-device order).

    Extra positional args flow through: ``vag(params, *batch)`` calls
    ``loss_fn(params, *batch)``.  ``has_aux`` follows
    ``jax.value_and_grad``.
    """
    if axis_names is None:
        axis_names = _all_axes(_default_mesh(mesh))
    axes = (axis_names,) if isinstance(axis_names, str) \
        else tuple(axis_names)
    cfg = runtime.config() if runtime.is_initialized() else None
    if op is None:
        op = "mean" if (cfg is None or cfg.gradsync_average) else "sum"
    if compress is None and cfg is not None:
        compress = cfg.gradsync_compress
    if compress not in (None, "none", "bf16"):
        raise ValueError(f"unknown gradient compression {compress!r}")
    template_leaves, template_def = jax.tree.flatten(params_template)
    if not template_leaves:
        raise ValueError("make_overlapped_grad_fn: empty parameter tree")
    if max_bytes is None:
        max_bytes = overlap_bucket_bytes(mesh)
    # Bucket assignment + per-bucket backend choice, planned once per
    # (template avals, axes, knobs) and replayed across builder calls
    # (torchmpi_tpu/planner.py — a decision-only plan).
    oplan = planner.plan_overlap(template_leaves, axes, op=op,
                                 backend=backend, compress=compress,
                                 max_bytes=max_bytes)
    if oplan is not None:
        firing = oplan.extra["firing"]
        bucket_impls: Sequence[Optional[Callable]] = oplan.impls
    else:
        firing = assign_overlap_buckets(template_leaves, max_bytes)
        bucket_impls = [None] * len(firing)
    total = len(firing)
    syncs = [_make_bucket_sync(k, total, axes, op, backend, compress,
                               impl=bucket_impls[k])
             for k in range(total)]
    if cfg is not None and cfg.obs != "off":
        from .. import obs

        obs.record_gradsync(total, op, compress == "bf16")

    def wrapped_loss(params, *args):
        leaves, treedef = jax.tree.flatten(params)
        if len(leaves) != len(template_leaves):
            raise ValueError(
                f"make_overlapped_grad_fn: params tree has {len(leaves)} "
                f"leaves, template had {len(template_leaves)}")
        token = jnp.zeros((), jnp.float32)
        new = list(leaves)
        # Forward chain order is REVERSE firing order: AD traverses the
        # token chain backwards, so the bucket applied last — bucket 0,
        # the deepest layers — fires first.
        for k in range(total - 1, -1, -1):
            xs = tuple(leaves[i] for i in firing[k])
            xs, token = syncs[k](xs, token)
            for i, v in zip(firing[k], xs):
                new[i] = v
        return loss_fn(jax.tree.unflatten(treedef, new), *args)

    return jax.value_and_grad(wrapped_loss, has_aux=has_aux)


def accumulate_gradients(loss_fn: Callable, params: PyTree, *batch: Any,
                         n_accum: int) -> Tuple[Any, PyTree]:
    """Microbatched gradient accumulation inside jit: split each batch
    array's leading axis into ``n_accum`` equal microbatches, run
    ``loss_fn(params, *microbatch) -> scalar loss`` under ``lax.scan``,
    and return ``(mean_loss, mean_grads)`` — numerically the full-batch
    gradient (for batch-size-independent losses like means over examples)
    at 1/n_accum the activation memory.

    The standard lever when the per-chip batch that keeps the MXU busy
    does not fit in HBM; composes with :func:`synchronize_gradients` /
    ``zero.update`` exactly like a plain ``value_and_grad`` result.
    """
    if n_accum <= 1:
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        return loss, grads

    def split(x):
        lead = x.shape[0]
        if lead % n_accum != 0:
            raise ValueError(
                f"batch leading axis {lead} not divisible by "
                f"n_accum={n_accum}")
        return x.reshape(n_accum, lead // n_accum, *x.shape[1:])

    mbs = tuple(jax.tree.map(split, b) for b in batch)
    zero_g = jax.tree.map(jnp.zeros_like, params)
    # Carry dtype from the loss itself (f64 under x64, bf16 losses, ...).
    mb0 = tuple(jax.tree.map(lambda x: x[0], b) for b in mbs)
    loss_aval = jax.eval_shape(loss_fn, params, *mb0)
    init_loss = jnp.zeros(loss_aval.shape, loss_aval.dtype)

    def body(carry, mb):
        loss_sum, g_sum = carry
        loss, grads = jax.value_and_grad(loss_fn)(params, *mb)
        return (loss_sum + loss,
                jax.tree.map(jnp.add, g_sum, grads)), None

    (loss_sum, g_sum), _ = jax.lax.scan(body, (init_loss, zero_g), mbs)
    inv = 1.0 / n_accum
    return loss_sum * inv, jax.tree.map(lambda g: g * inv, g_sum)


# ---------------------------------------------------------------------------
# Data-parallel step builder: the end-to-end TorchMPI recipe
# (broadcast params once; each step: local grads -> allreduce -> sgd)
# ---------------------------------------------------------------------------


def data_parallel_step(
    step_fn: Callable,
    *,
    mesh: Optional[Mesh] = None,
    batch_argnums: Sequence[int] = (2,),
    donate_argnums: Sequence[int] = (0, 1),
    max_inflight: Optional[int] = None,
    check_vma: bool = False,
) -> Callable:
    """Wrap ``step_fn(params, opt_state, batch, ...)`` into a jitted SPMD step.

    ``step_fn`` is written from one device's perspective on its local batch
    shard and must call :func:`synchronize_gradients` on its grads — exactly
    the reference's training-loop shape (SURVEY §4.3) with the allreduce
    inside the compiled step.  Params/opt_state are replicated; arguments
    listed in ``batch_argnums`` are sharded on their leading axis over all
    mesh axes.

    ``max_inflight`` bounds the number of dispatched-but-unfinished steps.
    XLA's CPU backend runs each simulated device's collective on a shared
    thread pool; an unbounded async queue can starve a collective rendezvous
    of its participant threads and abort the process, so the CPU default is a
    conservative 2 (double buffering).  On real TPU the default is 16 — deep
    enough to hide dispatch latency, bounded enough to cap device-memory
    pressure from donated buffers.
    """
    m = _default_mesh(mesh)
    axes = _all_axes(m)
    repl = P()
    shard = P(axes)

    def spec_for(i):
        return shard if i in set(batch_argnums) else repl

    def wrapped(*args):
        in_specs = tuple(spec_for(i) for i in range(len(args)))
        # check_vma stays False by default: under JAX's VMA type system,
        # differentiating replicated params against sharded batches makes
        # autodiff insert its own psum (the broadcast's transpose), so
        # gradients arrive pre-summed and an explicit synchronize_gradients
        # would be skipped/miscounted.  This library's contract is the
        # reference's: gradients are per-device until the user syncs them.
        # The cost: a step_fn that forgets synchronize_gradients returns
        # device 0's un-synced values silently — which is also exactly what
        # the reference did if you forgot synchronizeGradients.
        fn = shard_map(step_fn, mesh=m, in_specs=in_specs,
                       out_specs=repl, check_vma=check_vma)
        out = fn(*args)
        return out, completion_token(out)

    jitted = jax.jit(wrapped, donate_argnums=tuple(donate_argnums))
    # Opt-in static analysis (Config.analysis; docs/ANALYSIS.md): check
    # each new argument-shape signature once — the same cadence as jit's
    # own compile cache — before the delegate dispatches it.  Off (the
    # default) wraps nothing: the steady-state path is unchanged.
    cfg = runtime.config() if runtime.is_initialized() else None
    mode = getattr(cfg, "analysis", "off") if cfg is not None else "off"
    if mode in ("warn", "error"):
        from .. import analysis

        jitted = analysis.wrap_step(jitted, wrapped,
                                    label="data_parallel_step", mode=mode)
    if cfg is not None and cfg.obs != "off":
        from .. import obs

        obs.record_step_build("data_parallel_step")
    return throttle_dispatch(jitted, mesh=m, max_inflight=max_inflight)


def completion_token(out: PyTree):
    """Scalar derived from a step's outputs — depends on them, is never
    returned to the caller, hence never donated back in: always safe to
    block on.  Pair with :func:`throttle_dispatch` (step builders return
    ``(out, completion_token(out))`` from their jitted body)."""
    leaves = jax.tree.leaves(out)
    return (jnp.ravel(leaves[0])[0].astype(jnp.float32)
            if leaves else jnp.float32(0))


def throttle_dispatch(jitted: Callable, *, mesh: Optional[Mesh] = None,
                      max_inflight: Optional[int] = None) -> Callable:
    """Bound the dispatched-but-unfinished step window of a jitted step that
    returns ``(out, completion_token)`` — see :func:`data_parallel_step` for
    why (CPU collective-rendezvous starvation; device-memory pressure from
    donated buffers).  Returns a callable yielding ``out`` only."""
    if max_inflight is None:
        m = _default_mesh(mesh)
        platform = list(m.devices.flat)[0].platform
        max_inflight = 2 if platform == "cpu" else 16

    from collections import deque

    window: deque = deque()

    def throttled(*args):
        # Throttle *before* dispatch so donated inputs are still live.
        while len(window) >= max_inflight:
            jax.block_until_ready(window.popleft())
        out, token = jitted(*args)
        window.append(token)
        return out

    throttled.jitted = jitted  # escape hatch for benchmarking raw dispatch
    return throttled

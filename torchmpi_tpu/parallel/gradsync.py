"""Gradient/parameter synchronization for data-parallel SGD.

Rebuild of ``torchmpi.nn`` (SURVEY.md §3 C10, §4.3, reconstructed — reference
mount empty): ``synchronizeParameters(net)`` broadcast the parameters from
rank 0 at init; ``synchronizeGradients(net)`` allreduced gradParams after each
backward; an async variant overlapped per-layer allreduces with backprop.

TPU-native mapping:

- *Parameter sync* is a sharding statement: replicating the pytree over the
  mesh (``NamedSharding(mesh, P())``) makes every device hold rank-0's copy —
  the broadcast happens in the transfer.  An explicit in-axis broadcast is
  also provided for divergent-state repair (the reference's re-sync use case).
- *Gradient sync* is selector-routed ``allreduce_in_axis`` inside the jitted
  train step, so the hierarchical / custom backends apply to the hot path.
- *The async per-layer overlap* becomes **bucketing**: gradients are flattened
  into K buckets, each allreduced separately inside jit — XLA's latency-hiding
  scheduler overlaps bucket k's collective with bucket k+1's computation,
  playing the role of the reference's per-module hooks firing during backward.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import collectives, fusion, planner, runtime

PyTree = Any
AxisNames = Union[str, Tuple[str, ...]]


def _default_mesh(mesh: Optional[Mesh]) -> Mesh:
    return mesh if mesh is not None else runtime.current_mesh()


def _all_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(mesh.axis_names)


# ---------------------------------------------------------------------------
# Parameter synchronization (reference: mpinn.synchronizeParameters)
# ---------------------------------------------------------------------------


def synchronize_parameters(params: PyTree, *, mesh: Optional[Mesh] = None,
                           copy: bool = True) -> PyTree:
    """Replicate a parameter pytree across every device of the mesh.

    The reference broadcast ``net:parameters()`` from rank 0; here the
    replicating ``device_put`` *is* that broadcast (source: the controller's
    copy).  Returns the same values, now resident and replicated on the mesh.

    ``copy=True`` (default) breaks buffer aliasing with the input: a
    device_put of an on-device array can return an aliased buffer, and the
    usual next step donates the result into a train step — which would
    silently delete the caller's template.  This is an init-time op; the
    extra host round-trip is irrelevant.
    """
    m = _default_mesh(mesh)
    repl = NamedSharding(m, P())

    def put(a):
        if copy and isinstance(a, jax.Array):
            if a.is_fully_addressable:
                a = np.asarray(a)
            else:
                # Multi-host global array: host readback is impossible;
                # a device-side copy (fresh buffers, no donation) breaks
                # the aliasing just as well.
                a = jnp.copy(a)
        return jax.device_put(a, repl)

    return jax.tree.map(put, params)


def resynchronize_parameters_in_axis(params: PyTree, axis_names: AxisNames,
                                     *, root: int = 0,
                                     backend: Optional[str] = None) -> PyTree:
    """In-axis broadcast of params from ``root`` — for use inside shard_map
    when per-device state may have diverged (async PS training, debugging)."""
    return collectives.broadcast_in_axis(params, axis_names, root=root,
                                         backend=backend)


# ---------------------------------------------------------------------------
# Gradient synchronization (reference: mpinn.synchronizeGradients)
# ---------------------------------------------------------------------------


# The flatten/bucket/shard machinery is the fusion layer's FusedSpec —
# ONE definition shared by the fused in-axis collectives, the bucketed
# allreduce here, and ZeRO's shard layout (parallel/zero.py).  The old
# names stay importable: FlatSpec(tree, n_shards) is the same contract
# (single-dtype trees lay out byte-identically; mixed-dtype trees are
# now group-major so the wire never promotes).
FlatSpec = fusion.FusedSpec
flatten_tree = fusion.flatten_tree
unflatten_tree = fusion.unflatten_tree


def _wire_compress(compress, *, allowed=("bf16",), site: str):
    """Resolve/validate a wire-compression knob through the ONE shared
    helper (``torchmpi_tpu.compress.validate_wire`` — gradsync and zero
    used to each hand-roll the membership check).  The uncompressed
    fast path never imports the codec module."""
    if compress is None or compress in ("none", "off", ""):
        return None
    from .. import compress as _codec

    return _codec.validate_wire(compress, allowed=allowed, site=site)


def init_dcn_residuals(params_template: PyTree,
                       axis_names: Optional[AxisNames] = None, *,
                       mesh: Optional[Mesh] = None,
                       n_buckets: Optional[int] = None) -> List[jax.Array]:
    """Zero-initialized error-feedback residual state for
    :func:`synchronize_gradients` with a quantized DCN leg
    (docs/HIERARCHICAL.md): one f32 accumulator per gradient bucket,
    shaped ``[n_devices, shard]`` where ``shard`` is the bucket's
    ICI-scattered extent (the point where quantization happens).  Pass
    it through the train step sharded ``P(axes)`` on the leading axis
    and thread the returned state back in — the residual is persistent
    per-(site, bucket) state, exactly like optimizer state."""
    from .. import compress as _codec

    m = _default_mesh(mesh)
    if axis_names is None:
        axis_names = _all_axes(m)
    axes = _codec.ef_axes(axis_names)
    n_inner = int(m.shape[axes[1]])
    n_dev = int(np.prod([m.shape[a] for a in axes]))
    cfg = runtime.config() if runtime.is_initialized() else None
    if n_buckets is None:
        n_buckets = cfg.gradsync_buckets if cfg is not None else 1
    spec = fusion.FusedSpec(params_template, n_buckets=max(1, n_buckets))
    return _codec.init_residuals(
        _codec.expected_shards(
            [hi - lo for g in spec.groups for (lo, hi) in g.bounds],
            n_inner), n_dev)


def _dcn_ef_allreduce(grads: PyTree, axes: Tuple[str, ...], *, op: str,
                      n_buckets: int, codec: str, residuals
                      ) -> Tuple[PyTree, List]:
    """The error-feedback two-level gradient sync: per dtype-group
    bucket, reduce_scatter(ici) -> residual-corrected quantized
    allreduce(dcn) -> all_gather(ici) (``compress.ef_bucket_allreduce``
    — docs/HIERARCHICAL.md).  ``residuals`` is the per-bucket f32 state
    from :func:`init_dcn_residuals`; returns ``(synced, new_residuals)``
    with the new state in the old state's shapes."""
    from .. import compress

    outer, inner = axes
    spec = fusion.FusedSpec(grads, n_buckets=max(1, n_buckets))
    leaves = jax.tree.leaves(grads)
    launches = sum(len(g.bounds) for g in spec.groups)
    n_inner = lax.axis_size(inner)
    shard_lens = compress.expected_shards(
        [hi - lo for g in spec.groups for (lo, hi) in g.bounds], n_inner)
    res_list = compress.check_residuals(
        residuals, shard_lens, axes, site="synchronize_gradients",
        layout="the gradient bucket layout",
        init_hint="gradsync.init_dcn_residuals(params, ...) using the "
                  "SAME n_buckets/tree")
    from . import hierarchical

    min_bytes = runtime.effective_config().dcn_compress_min_bytes
    serialize = launches > 1 and hierarchical._serialize_collectives()
    out_leaves: List = [None] * spec.n_leaves
    new_res: List = []
    prev = None
    k = 0
    for g in spec.groups:
        flat = fusion.group_flat(leaves, g)
        parts = []
        for lo, hi in g.bounds:
            seg = flat[lo:hi]
            if serialize and prev is not None:
                # Each bucket is a psum_scatter/allreduce/all_gather
                # chain; unordered sibling chains deadlock the CPU
                # sim's blocking rendezvous (see
                # hierarchical._serialize_collectives) — chain bucket
                # i's input on bucket i-1's result there.
                seg, _ = lax.optimization_barrier((seg, prev))
            red, nr = compress.ef_bucket_allreduce(
                seg, outer, inner, codec, res_list[k], op=op,
                min_bytes=min_bytes)
            prev = red
            k += 1
            parts.append(red)
            new_res.append(nr)
        gout = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        fusion._unpack_group(gout, g, out_leaves)
    return jax.tree.unflatten(spec.treedef, out_leaves), new_res


def _bucketed_allreduce(grads: PyTree, axes: Tuple[str, ...], *, op: str,
                        n_buckets: int, backend: Optional[str],
                        barrier: bool = False) -> PyTree:
    """Per dtype group: concat -> ~K buckets -> one allreduce each ->
    unflatten (buckets distribute across groups by byte share; a
    single-dtype tree gets exactly K, the pre-fusion contract).

    The analog of the reference's async per-layer hooks (SURVEY §4.3): K
    independent collectives inside one jit give XLA the freedom to overlap
    them with surrounding compute.  Unlike the old promoted concat, each
    group reduces in its native dtype — a mixed fp32/bf16 tree keeps
    bf16 leaves bf16 on the wire.

    ``barrier=True`` chains each bucket's input on the previous bucket's
    output (across dtype groups too) through ``lax.optimization_barrier``,
    which keeps the K all-reduces DISTINCT through XLA's all-reduce
    combiner (measured: below the combine threshold the combiner
    otherwise merges every bucket into one collective —
    docs/artifacts/overlap_summary.md) and issues them in order, so the
    latency-hiding scheduler can overlap bucket i's downstream use with
    bucket i+1's collective.  The cost is serialization of the
    collectives themselves; leave it off when one fused all-reduce is
    fastest (small models).

    The bucketing spec and per-bucket backend choices are planned once
    per gradient-tree structure and replayed across step builds
    (:func:`torchmpi_tpu.planner.plan_gradsync`).
    """
    if not jax.tree.leaves(grads):
        return grads
    plan = planner.plan_gradsync(grads, axes, op=op, n_buckets=n_buckets,
                                 backend=backend, barrier=barrier)
    if plan is not None:
        return plan.replay(grads)
    spec = fusion.FusedSpec(grads, n_buckets=n_buckets)
    return fusion.fuse_tree("allreduce", grads, axes, backend=backend,
                            barrier=barrier, spec=spec, op=op)


def synchronize_gradients(grads: PyTree, axis_names: Optional[AxisNames] = None,
                          *, op: Optional[str] = None,
                          n_buckets: Optional[int] = None,
                          backend: Optional[str] = None,
                          compress: Optional[str] = None,
                          barrier: Optional[bool] = None,
                          residuals=None,
                          dcn_compress: Optional[str] = None) -> PyTree:
    """Allreduce a gradient pytree across the data-parallel axes.

    For use inside a shard_map'd/jitted train step (the hot path).  Defaults:
    axes = every axis of the current world mesh; ``op`` = mean when
    ``config.gradsync_average`` (the reference allreduce-summed then divided
    by ``mpi.size()``); ``n_buckets`` from config.

    ``compress="bf16"`` halves bytes on the wire by reducing in bfloat16 and
    casting back — the lever that matters when the allreduce is DCN-bound
    (multi-slice scaling); gradients tolerate it in practice.  Config
    default: ``gradsync_compress``.

    ``barrier`` (config default ``gradsync_barrier``) keeps bucketed
    all-reduces distinct through XLA's combiner via optimization
    barriers — see :func:`_bucketed_allreduce`.

    With ``n_buckets <= 1`` the tree rides the fused in-axis allreduce
    (``config.fuse_max_bytes``): dtype-grouped coalescing, O(dtypes x
    buckets) launches instead of one per leaf, bit-identical results.

    ``residuals`` (state from :func:`init_dcn_residuals`) switches to
    the **error-feedback quantized DCN path** on a two-level mesh
    (docs/HIERARCHICAL.md): per-bucket reduce_scatter over ICI, the
    small shard crossing DCN quantized with ``dcn_compress`` (default
    ``config.dcn_compress`` — must not be off) after adding back the
    persistent residual, and the new quantization error returned as the
    next step's state: ``(synced_grads, new_residuals)``.  On a flat
    (``n_dcn <= 1``) span there is no DCN leg — the call degrades to
    the plain path and returns the residuals unchanged (the selector's
    topology-fallback counter notes it; being the plain path, it honors
    the config-level ``gradsync_compress``/``gradsync_barrier`` knobs
    exactly as a residual-free call would).  The two-level EF schedule
    itself is fixed: explicit ``backend=``/``compress=``/
    ``barrier=True`` raise, and config-level ``gradsync_compress``/
    ``gradsync_barrier`` do not apply to it (the DCN codec is the wire
    compression; the schedule orders its own DCN legs).
    """
    if axis_names is None:
        axis_names = _all_axes(runtime.current_mesh())
    axes = (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)
    cfg = runtime.config() if runtime.is_initialized() else None
    if op is None:
        op = "mean" if (cfg is None or cfg.gradsync_average) else "sum"
    if n_buckets is None:
        n_buckets = cfg.gradsync_buckets if cfg is not None else 1
    explicit_compress = compress is not None
    if compress is None and cfg is not None:
        compress = cfg.gradsync_compress
    compress = _wire_compress(compress, site="synchronize_gradients")
    explicit_barrier = barrier is not None
    if barrier is None:
        barrier = cfg.gradsync_barrier if cfg is not None else False
    if residuals is not None:
        if explicit_barrier and barrier:
            # Same contract as the resolve_ef backend=/compress=
            # policing: the EF collective is a fixed two-level schedule
            # that orders its own legs — silently dropping the knob
            # would be invisible to the caller.
            raise ValueError(
                "synchronize_gradients: barrier= does not combine with "
                "error-feedback residuals — the EF schedule orders its "
                "own collectives (the config-level gradsync_barrier "
                "knob is what the flat-span degradation honors)")
        # One shared activation gate (compress.resolve_ef): codec
        # required, explicit backend=/compress= raise — the EF path
        # dispatches a FIXED two-level schedule (config-level
        # gradsync_compress/gradsync_barrier do not apply to it; the
        # flat-span degradation below is the plain path and honors
        # them as usual — see the docstring).
        from .. import compress as _codec_mod

        codec = _codec_mod.resolve_ef(
            dcn_compress, cfg, site="synchronize_gradients",
            backend=backend, explicit_compress=explicit_compress,
            compress=compress)
        _codec_mod.ef_axes(axes)
        if lax.axis_size(axes[0]) <= 1:
            # Flat span: no DCN crossing to compress.  Same graceful
            # degradation as the selector's hierarchical fallback.  The
            # recursive plain-path call records the round under its own
            # (uncompressed) label — recording "dcn-<codec>" here would
            # double-count the round and claim a codec that never ran.
            # The resolved compress is passed through EXPLICITLY
            # ("none" when uncompressed) so an explicit compress="none"
            # opt-out is not re-resolved from config by the inner call.
            from .. import selector as _sel

            _sel._note_fallback("allreduce", "dcn-" + codec,
                                "flat mesh (n_dcn <= 1)",
                                target="the plain sync path")
            out = synchronize_gradients(grads, axes, op=op,
                                        n_buckets=n_buckets,
                                        backend=backend,
                                        compress=compress or "none",
                                        barrier=barrier)
            return out, residuals
        if cfg is not None and cfg.obs != "off":
            from .. import obs

            obs.record_gradsync(max(1, n_buckets), op, f"dcn-{codec}")
        synced, new_res = _dcn_ef_allreduce(grads, axes, op=op,
                                            n_buckets=n_buckets,
                                            codec=codec,
                                            residuals=residuals)
        if cfg is not None and cfg.guard in ("numeric", "full"):
            # Numeric tripwire on the synced output (docs/GUARD.md) —
            # trace-time gate, one fused reduction; off adds nothing.
            # The residuals revert to the PRE-step state under the same
            # verdict: a tripped round's error mass must not re-enter
            # the next step through the EF accumulator (code review).
            from .. import guard

            synced, new_res = guard.check_tree(
                synced, site="gradsync",
                aux=list(zip(new_res, residuals)))
        return synced, new_res
    if cfg is not None and cfg.obs != "off":
        from .. import obs

        obs.record_gradsync(n_buckets, op, compress)
    orig_dtypes = None
    if compress == "bf16":
        orig_dtypes = jax.tree.map(lambda g: g.dtype, grads)
        grads = jax.tree.map(lambda g: g.astype(jnp.bfloat16), grads)
    if n_buckets <= 1:
        out = collectives.allreduce_in_axis(grads, axes, op=op,
                                            backend=backend)
    else:
        out = _bucketed_allreduce(grads, axes, op=op, n_buckets=n_buckets,
                                  backend=backend, barrier=barrier)
    if orig_dtypes is not None:
        out = jax.tree.map(lambda g, d: g.astype(d), out, orig_dtypes)
    if cfg is not None and cfg.guard in ("numeric", "full"):
        # Numeric tripwire fused onto the synced gradients
        # (docs/GUARD.md): one sum-of-squares reduction over the round;
        # skip_step zeroes the whole update when tripped, raise
        # surfaces NumericAnomalyError.  Trace-time gate — guard="off"
        # adds zero branches to the compiled step.
        from .. import guard

        out = guard.check_tree(out, site="gradsync")
    return out


# ---------------------------------------------------------------------------
# Backprop-overlapped gradient sync (docs/OVERLAP.md).  The reference's
# async per-layer hooks fired an allreduce per module as its gradParams
# arrived during backward; the TPU-native equivalent wraps each gradient
# BUCKET's parameters in a custom_vjp whose backward rule IS the
# bucket's allreduce — the collective then sits in the backward graph at
# exactly the point where that bucket's cotangents are complete, and the
# latency-hiding scheduler hides it under the remaining backward
# compute.  An optimization-barrier token chain (the gradsync_barrier
# machinery, threaded through the custom_vjp rules) keeps the buckets
# distinct through XLA's all-reduce combiner and issues them in
# materialization order.
# ---------------------------------------------------------------------------


def overlap_bucket_bytes(mesh: Optional[Mesh] = None) -> int:
    """Byte bound for one overlap bucket: ``config.
    gradsync_overlap_bytes`` when set, else the tuning-plan-aligned
    bound (:func:`torchmpi_tpu.tuning.plan_bucket_bytes`) — the largest
    measured allreduce size bucket for this mesh when a plan is active,
    else ``fuse_max_bytes`` rounded down to a plan bucket edge.  Sizing
    from the plan's log2 buckets (instead of a fixed ``n_buckets``)
    keys every fired bucket to a collective size somebody measured."""
    cfg = runtime.effective_config()
    if cfg.gradsync_overlap_bytes > 0:
        return int(cfg.gradsync_overlap_bytes)
    from .. import tuning

    m = _default_mesh(mesh)
    return tuning.plan_bucket_bytes("allreduce", m,
                                    cfg.fuse_max_bytes or 32 * 1024 * 1024)


def assign_overlap_buckets(leaves: Sequence, max_bytes: int
                           ) -> List[List[int]]:
    """Reverse-parameter-order bucket assignment: walk the flattened
    tree's leaves LAST to FIRST — the order their cotangents
    materialize during backprop — starting a new bucket when the byte
    bound fills or the dtype changes (buckets stay dtype-pure, the
    fusion discipline: a mixed fp32/bf16 tree never promotes on the
    wire).  Returns buckets of leaf indices in FIRING order: bucket 0
    (the deepest layers) launches first."""
    max_bytes = max(1, int(max_bytes))
    buckets: List[List[int]] = []
    acc = 0
    cur_dt = None
    for i in range(len(leaves) - 1, -1, -1):
        leaf = leaves[i]
        b = int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        if (not buckets or np.dtype(leaf.dtype) != cur_dt
                or acc + b > max_bytes):
            buckets.append([])
            acc = 0
            cur_dt = np.dtype(leaf.dtype)
        buckets[-1].append(i)
        acc += b
    return buckets


def _make_bucket_sync(idx: int, total: int, axes: Tuple[str, ...],
                      op: str, backend: Optional[str],
                      compress: Optional[str],
                      impl: Optional[Callable] = None,
                      dcn_codec: Optional[str] = None):
    """One bucket's sync op: identity in forward, THE bucket's
    allreduce in backward.  ``token`` threads the optimization-barrier
    chain across buckets: the backward rule barriers its allreduce
    input on the incoming token (the previous-fired bucket's launch)
    and derives its outgoing token from the allreduce result — so the
    collectives stay distinct through the combiner and issue in firing
    order, each eligible the moment its cotangents exist.  ``impl`` is
    the planner's pre-picked allreduce implementation for this bucket
    (None falls back to a per-trace selector pick).

    ``dcn_codec`` switches the backward rule to the error-feedback
    two-level allreduce (``compress.ef_bucket_allreduce``): the sync
    then takes a third ``res`` argument (this bucket's persistent f32
    residual) whose "cotangent" slot carries the NEW residual out —
    the state rides the AD graph, so it updates exactly when the
    bucket's collective fires, inside the backward pass."""

    def _pre(g, tok):
        """Shared bwd prologue: obs grads event, concat, barrier on the
        previous bucket's launch, obs launch event."""
        shapes = [x.shape for x in g]
        sizes = [int(np.prod(s)) for s in shapes]
        obs_on = runtime.effective_config().obs != "off"
        if obs_on:
            from .. import obs

            # Runtime evidence, not trace-time: the callback fires when
            # this bucket's cotangents materialize on each device — the
            # flight-ring ordering of grads/launch events across
            # buckets is the CPU-sim-checkable overlap invariant.
            jax.debug.callback(
                lambda *_a, _o=obs, _k=idx, _t=total:
                _o.record_overlap("grads", _k, _t),
                g[0].reshape(-1)[:1])
        flat = (g[0].reshape(-1) if len(g) == 1
                else jnp.concatenate([x.reshape(-1) for x in g]))
        flat, _ = lax.optimization_barrier((flat, tok))
        if obs_on:
            from .. import obs

            jax.debug.callback(
                lambda *_a, _o=obs, _k=idx, _t=total:
                _o.record_overlap("launch", _k, _t),
                flat[:1])
        return flat, shapes, sizes

    def _post(red, tok, shapes, sizes):
        """Shared bwd epilogue: outgoing token + per-leaf unflatten."""
        anchor = red[0] if sum(sizes) else tok
        tok_out, _ = lax.optimization_barrier((tok, anchor))
        out, off = [], 0
        for s, sz in zip(shapes, sizes):
            out.append(red[off:off + sz].reshape(s))
            off += sz
        return tuple(out), tok_out

    if dcn_codec is not None:
        outer, inner = axes

        @jax.custom_vjp
        def sync_ef(xs, token, res):
            return xs, token

        def fwd_ef(xs, token, res):
            return (xs, token), res

        def bwd_ef(res, cts):
            from .. import compress as _codec

            g, tok = cts
            flat, shapes, sizes = _pre(g, tok)
            red, new_res = _codec.ef_bucket_allreduce(
                flat, outer, inner, dcn_codec, res, op=op,
                min_bytes=runtime.effective_config()
                .dcn_compress_min_bytes)
            red = red.astype(flat.dtype)
            if runtime.effective_config().guard in ("numeric", "full"):
                # Numeric tripwire per overlap bucket (docs/GUARD.md):
                # fused into the same backward rule that fired the
                # collective — trace-time gate, zero cost when off.
                # The bucket's EF residual reverts to its pre-step
                # state under the same verdict (code review: a tripped
                # round's error mass must not ride the accumulator
                # into the next step).
                from .. import guard

                red, (new_res,) = guard.check_flat(
                    red, site="overlap", bucket=idx,
                    aux=[(new_res, res)])
            out, tok_out = _post(red, tok, shapes, sizes)
            return (out, tok_out, new_res)

        sync_ef.defvjp(fwd_ef, bwd_ef)
        return sync_ef

    @jax.custom_vjp
    def sync(xs, token):
        return xs, token

    def fwd(xs, token):
        return (xs, token), None

    def bwd(_, cts):
        g, tok = cts
        flat, shapes, sizes = _pre(g, tok)
        orig_dtype = flat.dtype
        if compress == "bf16":
            flat = flat.astype(jnp.bfloat16)
        bucket_impl = impl
        if bucket_impl is None:
            bucket_impl = collectives._pick(  # noqa: SLF001 — shared route
                "allreduce", flat, backend, axes)
        red = bucket_impl(flat, axes, op=op)
        if compress == "bf16":
            red = red.astype(orig_dtype)
        if runtime.effective_config().guard in ("numeric", "full"):
            # Numeric tripwire per overlap bucket (docs/GUARD.md):
            # fused into the same backward rule that fired the
            # collective — trace-time gate, zero cost when off.
            from .. import guard

            red = guard.check_flat(red, site="overlap", bucket=idx)
        out, tok_out = _post(red, tok, shapes, sizes)
        return (out, tok_out)

    sync.defvjp(fwd, bwd)
    return sync


def init_overlap_dcn_residuals(params_template: PyTree,
                               axis_names: Optional[AxisNames] = None, *,
                               mesh: Optional[Mesh] = None,
                               max_bytes: Optional[int] = None
                               ) -> List[jax.Array]:
    """Zero-initialized error-feedback residual state for
    :func:`make_overlapped_grad_fn` with a quantized DCN leg: one f32
    accumulator per FIRING-ORDER overlap bucket (the reverse-parameter
    ``assign_overlap_buckets`` layout), shaped ``[n_devices, shard]``
    like :func:`init_dcn_residuals`."""
    from .. import compress as _codec

    m = _default_mesh(mesh)
    if axis_names is None:
        axis_names = _all_axes(m)
    axes = _codec.ef_axes(axis_names)
    n_inner = int(m.shape[axes[1]])
    n_dev = int(np.prod([m.shape[a] for a in axes]))
    leaves = jax.tree.leaves(params_template)
    if max_bytes is None:
        max_bytes = overlap_bucket_bytes(m)
    firing = assign_overlap_buckets(leaves, max_bytes)
    return _codec.init_residuals(
        _codec.expected_shards(
            [sum(int(np.prod(leaves[i].shape)) for i in bucket)
             for bucket in firing], n_inner), n_dev)


def make_overlapped_grad_fn(loss_fn: Callable, params_template: PyTree,
                            axis_names: Optional[AxisNames] = None, *,
                            mesh: Optional[Mesh] = None,
                            op: Optional[str] = None,
                            backend: Optional[str] = None,
                            compress: Optional[str] = None,
                            has_aux: bool = False,
                            max_bytes: Optional[int] = None,
                            residuals: bool = False,
                            dcn_compress: Optional[str] = None) -> Callable:
    """Build a ``value_and_grad`` whose gradients come back ALREADY
    allreduced, with each bucket's collective fired inside the backward
    pass as its cotangents materialize (the DDP overlap schedule; the
    reference's async per-layer hooks).

    For use INSIDE a shard_map'd/jitted train step, where
    ``synchronize_gradients`` would otherwise run after the full
    backward::

        vag = gradsync.make_overlapped_grad_fn(loss_fn, params, axes)
        loss, grads = vag(params, batch)      # grads are synced

    ``params_template`` supplies leaf shapes/dtypes for the bucket
    assignment — the traced ``params`` themselves work (the recipes
    step builders do exactly that), as does an ``eval_shape`` tree.
    Buckets are assigned in reverse parameter order (:func:
    `assign_overlap_buckets`) and sized from the tuning-plan size
    buckets (:func:`overlap_bucket_bytes`) unless ``max_bytes`` is
    given.  Defaults: ``op`` from ``config.gradsync_average``,
    ``compress`` from ``config.gradsync_compress`` — exactly
    :func:`synchronize_gradients`'s, and the results are bit-identical
    to it (test-asserted; the fused reductions are elementwise over
    the same cross-device order).

    Extra positional args flow through: ``vag(params, *batch)`` calls
    ``loss_fn(params, *batch)``.  ``has_aux`` follows
    ``jax.value_and_grad``.

    ``residuals=True`` arms the **error-feedback quantized DCN leg**
    (``dcn_compress``, default ``config.dcn_compress`` — must not be
    off; docs/HIERARCHICAL.md): each bucket's backward-pass collective
    becomes the two-level EF allreduce, and the returned callable takes
    the residual state as its SECOND argument —
    ``vag(params, residuals, *batch) -> (loss, (grads,
    new_residuals))`` — with the new state emerging through the
    residual slot of ``value_and_grad`` (the state update happens
    inside the backward pass, exactly when the bucket fires).  Build
    the state with :func:`init_overlap_dcn_residuals` using the same
    template/``max_bytes``.  On a flat (``n_dcn <= 1``) mesh the
    builder degrades to the plain overlap schedule — same calling
    convention, residuals handed back unchanged, the selector's
    topology-fallback counter notes it.  Explicit ``backend=``/
    ``compress=`` raise with ``residuals=True`` (the EF buckets run a
    fixed two-level schedule).
    """
    if axis_names is None:
        axis_names = _all_axes(_default_mesh(mesh))
    axes = (axis_names,) if isinstance(axis_names, str) \
        else tuple(axis_names)
    cfg = runtime.config() if runtime.is_initialized() else None
    if op is None:
        op = "mean" if (cfg is None or cfg.gradsync_average) else "sum"
    explicit_compress = compress is not None
    if compress is None and cfg is not None:
        compress = cfg.gradsync_compress
    compress = _wire_compress(compress, site="make_overlapped_grad_fn")
    codec = None
    ef_passthrough = False
    if residuals:
        # Same shared activation gate as synchronize_gradients
        # (compress.resolve_ef): codec required, explicit
        # backend=/compress= raise — the EF buckets run a FIXED
        # two-level schedule.
        from .. import compress as _codec_mod

        codec = _codec_mod.resolve_ef(
            dcn_compress, cfg, site="make_overlapped_grad_fn",
            backend=backend, explicit_compress=explicit_compress,
            compress=compress)
        _codec_mod.ef_axes(axes)
        if int(_default_mesh(mesh).shape[axes[0]]) <= 1:
            # Flat span: no DCN crossing to compress.  Degrade AT BUILD
            # TIME to the plain overlap schedule (bit-identical grads,
            # no pointless quantization) and thread the residual state
            # through unchanged — the same graceful fallback as
            # synchronize_gradients/zero, counted the same way.
            from .. import selector as _sel

            _sel._note_fallback("allreduce", "dcn-" + codec,
                                "flat mesh (n_dcn <= 1)",
                                target="the plain overlap schedule")
            codec = None
            ef_passthrough = True
    template_leaves, template_def = jax.tree.flatten(params_template)
    if not template_leaves:
        raise ValueError("make_overlapped_grad_fn: empty parameter tree")
    if max_bytes is None:
        max_bytes = overlap_bucket_bytes(mesh)
    # Bucket assignment + per-bucket backend choice, planned once per
    # (template avals, axes, knobs) and replayed across builder calls
    # (torchmpi_tpu/planner.py — a decision-only plan).  The EF path
    # uses the firing assignment only: its collective is the fixed
    # two-level schedule, not a selector pick.
    oplan = planner.plan_overlap(template_leaves, axes, op=op,
                                 backend=backend, compress=compress,
                                 max_bytes=max_bytes, dcn_codec=codec)
    if oplan is not None:
        firing = oplan.extra["firing"]
        bucket_impls: Sequence[Optional[Callable]] = oplan.impls
    else:
        firing = assign_overlap_buckets(template_leaves, max_bytes)
        bucket_impls = [None] * len(firing)
    total = len(firing)
    syncs = [_make_bucket_sync(k, total, axes, op, backend, compress,
                               impl=bucket_impls[k], dcn_codec=codec)
             for k in range(total)]
    if cfg is not None and cfg.obs != "off":
        from .. import obs

        obs.record_gradsync(total, op,
                            f"dcn-{codec}" if codec else compress)

    def _chain(params, res_list, *args):
        leaves, treedef = jax.tree.flatten(params)
        if len(leaves) != len(template_leaves):
            raise ValueError(
                f"make_overlapped_grad_fn: params tree has {len(leaves)} "
                f"leaves, template had {len(template_leaves)}")
        token = jnp.zeros((), jnp.float32)
        new = list(leaves)
        # Forward chain order is REVERSE firing order: AD traverses the
        # token chain backwards, so the bucket applied last — bucket 0,
        # the deepest layers — fires first.
        for k in range(total - 1, -1, -1):
            xs = tuple(leaves[i] for i in firing[k])
            if res_list is None:
                xs, token = syncs[k](xs, token)
            else:
                xs, token = syncs[k](xs, token, res_list[k])
            for i, v in zip(firing[k], xs):
                new[i] = v
        return loss_fn(jax.tree.unflatten(treedef, new), *args)

    if codec is None:
        def wrapped_loss(params, *args):
            return _chain(params, None, *args)

        plain = jax.value_and_grad(wrapped_loss, has_aux=has_aux)
        if not ef_passthrough:
            return plain

        def degraded_ef(params, residual_state, *args):
            # Flat-span EF degradation: plain overlapped grads, the
            # caller's residual state handed back unchanged in the EF
            # calling convention ((loss, (grads, residuals))).
            out, grads = plain(params, *args)
            return out, (grads, residual_state)

        return degraded_ef

    from .. import compress as _codec_mod

    # Expected per-bucket residual extents (the shared
    # compress.expected_shards formula init_overlap_dcn_residuals
    # builds with), so a wrong-SIZE state fails here with provenance
    # instead of as a raw reshape error deep in the backward pass.
    _ef_n_inner = int(_default_mesh(mesh).shape[axes[1]])
    _ef_shards = _codec_mod.expected_shards(
        [sum(int(np.prod(template_leaves[i].shape)) for i in bucket)
         for bucket in firing], _ef_n_inner)

    def wrapped_loss_ef(params, residual_state, *args):
        res_list = _codec_mod.check_residuals(
            residual_state, _ef_shards, axes,
            site="make_overlapped_grad_fn",
            layout="the overlap bucket layout",
            init_hint="gradsync.init_overlap_dcn_residuals(template, "
                      "...) using the SAME template/max_bytes")
        return _chain(params, res_list, *args)

    # The residual argnum rides value_and_grad: its "gradient" IS the
    # new residual state (fabricated by the custom_vjp bwd rules), so
    # callers get (loss, (grads, new_residuals)) from one call.
    return jax.value_and_grad(wrapped_loss_ef, argnums=(0, 1),
                              has_aux=has_aux)


def accumulate_gradients(loss_fn: Callable, params: PyTree, *batch: Any,
                         n_accum: int) -> Tuple[Any, PyTree]:
    """Microbatched gradient accumulation inside jit: split each batch
    array's leading axis into ``n_accum`` equal microbatches, run
    ``loss_fn(params, *microbatch) -> scalar loss`` under ``lax.scan``,
    and return ``(mean_loss, mean_grads)`` — numerically the full-batch
    gradient (for batch-size-independent losses like means over examples)
    at 1/n_accum the activation memory.

    The standard lever when the per-chip batch that keeps the MXU busy
    does not fit in HBM; composes with :func:`synchronize_gradients` /
    ``zero.update`` exactly like a plain ``value_and_grad`` result.
    """
    if n_accum <= 1:
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        return loss, grads

    def split(x):
        lead = x.shape[0]
        if lead % n_accum != 0:
            raise ValueError(
                f"batch leading axis {lead} not divisible by "
                f"n_accum={n_accum}")
        return x.reshape(n_accum, lead // n_accum, *x.shape[1:])

    mbs = tuple(jax.tree.map(split, b) for b in batch)
    zero_g = jax.tree.map(jnp.zeros_like, params)
    # Carry dtype from the loss itself (f64 under x64, bf16 losses, ...).
    mb0 = tuple(jax.tree.map(lambda x: x[0], b) for b in mbs)
    loss_aval = jax.eval_shape(loss_fn, params, *mb0)
    init_loss = jnp.zeros(loss_aval.shape, loss_aval.dtype)

    def body(carry, mb):
        loss_sum, g_sum = carry
        loss, grads = jax.value_and_grad(loss_fn)(params, *mb)
        return (loss_sum + loss,
                jax.tree.map(jnp.add, g_sum, grads)), None

    (loss_sum, g_sum), _ = jax.lax.scan(body, (init_loss, zero_g), mbs)
    inv = 1.0 / n_accum
    return loss_sum * inv, jax.tree.map(lambda g: g * inv, g_sum)


# ---------------------------------------------------------------------------
# Data-parallel step builder: the end-to-end TorchMPI recipe
# (broadcast params once; each step: local grads -> allreduce -> sgd)
# ---------------------------------------------------------------------------


def data_parallel_step(
    step_fn: Callable,
    *,
    mesh: Optional[Mesh] = None,
    batch_argnums: Sequence[int] = (2,),
    donate_argnums: Sequence[int] = (0, 1),
    max_inflight: Optional[int] = None,
    check_vma: bool = False,
) -> Callable:
    """Wrap ``step_fn(params, opt_state, batch, ...)`` into a jitted SPMD step.

    ``step_fn`` is written from one device's perspective on its local batch
    shard and must call :func:`synchronize_gradients` on its grads — exactly
    the reference's training-loop shape (SURVEY §4.3) with the allreduce
    inside the compiled step.  Params/opt_state are replicated; arguments
    listed in ``batch_argnums`` are sharded on their leading axis over all
    mesh axes.

    ``max_inflight`` bounds the number of dispatched-but-unfinished steps.
    XLA's CPU backend runs each simulated device's collective on a shared
    thread pool; an unbounded async queue can starve a collective rendezvous
    of its participant threads and abort the process, so the CPU default is a
    conservative 2 (double buffering).  On real TPU the default is 16 — deep
    enough to hide dispatch latency, bounded enough to cap device-memory
    pressure from donated buffers.
    """
    m = _default_mesh(mesh)
    axes = _all_axes(m)
    repl = P()
    shard = P(axes)

    def spec_for(i):
        return shard if i in set(batch_argnums) else repl

    def wrapped(*args):
        in_specs = tuple(spec_for(i) for i in range(len(args)))
        # check_vma stays False by default: under JAX's VMA type system,
        # differentiating replicated params against sharded batches makes
        # autodiff insert its own psum (the broadcast's transpose), so
        # gradients arrive pre-summed and an explicit synchronize_gradients
        # would be skipped/miscounted.  This library's contract is the
        # reference's: gradients are per-device until the user syncs them.
        # The cost: a step_fn that forgets synchronize_gradients returns
        # device 0's un-synced values silently — which is also exactly what
        # the reference did if you forgot synchronizeGradients.
        fn = shard_map(step_fn, mesh=m, in_specs=in_specs,
                       out_specs=repl, check_vma=check_vma)
        out = fn(*args)
        return out, completion_token(out)

    jitted = jax.jit(wrapped, donate_argnums=tuple(donate_argnums))
    # Opt-in static analysis (Config.analysis; docs/ANALYSIS.md): check
    # each new argument-shape signature once — the same cadence as jit's
    # own compile cache — before the delegate dispatches it.  Off (the
    # default) wraps nothing: the steady-state path is unchanged.
    cfg = runtime.config() if runtime.is_initialized() else None
    mode = getattr(cfg, "analysis", "off") if cfg is not None else "off"
    if mode in ("warn", "error"):
        from .. import analysis

        jitted = analysis.wrap_step(jitted, wrapped,
                                    label="data_parallel_step", mode=mode)
    stepper = throttle_dispatch(jitted, mesh=m, max_inflight=max_inflight)
    if cfg is not None and cfg.obs != "off":
        # Build-time gate (the never-imported-when-off discipline): the
        # per-call cost when on is one ring append marking the step
        # boundary BEFORE dispatch — the window edge obs_tool
        # attribute budgets against.
        from .. import obs

        obs.record_step_build("data_parallel_step")
        inner = stepper
        counter = [0]

        def stepper(*args):  # noqa: F811 — deliberate rebind
            obs.record_step("data_parallel_step", counter[0])
            counter[0] += 1
            return inner(*args)

        stepper.jitted = jitted
    if cfg is not None and cfg.guard in ("numeric", "full"):
        # The numeric tripwire's raise-policy boundary (docs/GUARD.md):
        # a tripped bucket is zeroed in-graph, and the deferred typed
        # error surfaces HERE, on the eager side of the dispatch — up
        # to max_inflight steps after the trip (the in-flight window).
        # Build-time gate: guard="off" returns the bare stepper.
        from .. import guard

        def guarded(*args):
            out = stepper(*args)
            guard.raise_pending()
            return out

        guarded.jitted = jitted
        return guarded
    return stepper


def completion_token(out: PyTree):
    """Scalar derived from a step's outputs — depends on them, is never
    returned to the caller, hence never donated back in: always safe to
    block on.  Pair with :func:`throttle_dispatch` (step builders return
    ``(out, completion_token(out))`` from their jitted body)."""
    leaves = jax.tree.leaves(out)
    return (jnp.ravel(leaves[0])[0].astype(jnp.float32)
            if leaves else jnp.float32(0))


def throttle_dispatch(jitted: Callable, *, mesh: Optional[Mesh] = None,
                      max_inflight: Optional[int] = None) -> Callable:
    """Bound the dispatched-but-unfinished step window of a jitted step that
    returns ``(out, completion_token)`` — see :func:`data_parallel_step` for
    why (CPU collective-rendezvous starvation; device-memory pressure from
    donated buffers).  Returns a callable yielding ``out`` only."""
    if max_inflight is None:
        m = _default_mesh(mesh)
        platform = list(m.devices.flat)[0].platform
        max_inflight = 2 if platform == "cpu" else 16

    from collections import deque

    window: deque = deque()

    def throttled(*args):
        # Throttle *before* dispatch so donated inputs are still live.
        while len(window) >= max_inflight:
            jax.block_until_ready(window.popleft())
        out, token = jitted(*args)
        window.append(token)
        return out

    throttled.jitted = jitted  # escape hatch for benchmarking raw dispatch
    return throttled

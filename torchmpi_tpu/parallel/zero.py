"""ZeRO-1 data parallelism: shard the optimizer state over the DP axes.

Beyond-reference (TorchMPI is replicated-state DP only — SURVEY.md §3.3),
but it is the natural TPU-native evolution of the same allreduce step: the
allreduce decomposes into reduce_scatter + shard-local optimizer update +
all_gather (numerically identical to replicated DP), and the optimizer
state then only ever exists for each device's 1/n shard — an n-fold cut of
the largest replicated memory term after the params themselves.  On a
(dcn, ici) mesh the reduce_scatter/all_gather legs ride the same
selector-routed collectives as :func:`gradsync.synchronize_gradients`.

Usage, inside a ``shard_map``-based train step (per-device code)::

    opt_state = zero.init(params, tx, axes, mesh=mesh)   # sharded state
    ...
    def step(params, opt_state, batch):
        grads = jax.grad(loss)(params, batch)
        params, opt_state = zero.update(params, grads, opt_state, tx, axes)
        ...

or end-to-end via ``recipes.make_bn_dp_train_step(..., zero=True)``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .. import collectives, runtime

PyTree = Any
AxisNames = Union[str, Tuple[str, ...]]


def _axes_tuple(axis_names: AxisNames) -> Tuple[str, ...]:
    return ((axis_names,) if isinstance(axis_names, str)
            else tuple(axis_names))


def _axis_size(axes: Tuple[str, ...]) -> Any:
    n = 1
    for a in axes:
        n *= lax.axis_size(a)
    return n


def _axis_index(axes: Tuple[str, ...]):
    """Linearized device index over ``axes``, row-major in the given order —
    the same linearization ``lax.psum_scatter`` uses for tile assignment."""
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


# The flatten/pad/unflatten machinery is shared with the bucketed
# allreduce — one definition in gradsync.
from .gradsync import (FlatSpec as _FlatSpec,  # noqa: E402
                       flatten_tree as _flatten,
                       unflatten_tree as _unflatten)


def _resolve(axis_names: Optional[AxisNames], mesh: Optional[Mesh]
             ) -> Tuple[Mesh, Tuple[str, ...], int]:
    m = mesh if mesh is not None else runtime.current_mesh()
    axes = _axes_tuple(axis_names) if axis_names is not None \
        else tuple(m.axis_names)
    n = int(np.prod([m.shape[a] for a in axes]))
    return m, axes, n


def specs_like(state: PyTree, axis_names: AxisNames) -> PyTree:
    """PartitionSpec tree matching an existing ZeRO state pytree: the ONE
    definition of which leaves are sharded — per-parameter leaves
    (ndim >= 1) ``P(axes)``, scalar leaves (step counts) replicated.
    ``state`` may hold arrays or tracers (``jnp.ndim`` handles both), so
    step builders can call this on their traced inputs."""
    axes = _axes_tuple(axis_names)
    return jax.tree.map(
        lambda l: P(axes) if jnp.ndim(l) >= 1 else P(), state)


def state_specs(params: PyTree, tx: optax.GradientTransformation,
                axis_names: Optional[AxisNames] = None, *,
                mesh: Optional[Mesh] = None) -> PyTree:
    """PartitionSpec tree for the ZeRO-1 optimizer state: per-parameter
    leaves (ndim >= 1) sharded ``P(axes)``, scalar leaves (step counts)
    replicated.  Shared by :func:`init` and step builders that thread the
    state through their own shard_map."""
    m, axes, n = _resolve(axis_names, mesh)
    spec = _FlatSpec(params, n)
    shard_shape = jax.ShapeDtypeStruct((spec.shard,), spec.dtype)
    state_shapes = jax.eval_shape(tx.init, shard_shape)
    return specs_like(state_shapes, axes)


def init(params: PyTree, tx: optax.GradientTransformation,
         axis_names: Optional[AxisNames] = None, *,
         mesh: Optional[Mesh] = None) -> PyTree:
    """Build the optimizer state for ZeRO-1: state over each device's flat
    parameter shard, physically sharded across ``axis_names``.

    Runs its own jitted shard_map (init-time convenience, like
    ``synchronize_parameters``); the result feeds :func:`update` inside the
    train step.
    """
    m, axes, n = _resolve(axis_names, mesh)
    spec = _FlatSpec(params, n)
    specs = state_specs(params, tx, axes, mesh=m)

    def body(params):
        p_shard = lax.dynamic_slice(
            _flatten(params, spec), (_axis_index(axes) * spec.shard,),
            (spec.shard,))
        return tx.init(p_shard)

    return jax.jit(shard_map(
        body, mesh=m, in_specs=P(), out_specs=specs,
        check_vma=False))(params)


def update(params: PyTree, grads: PyTree, opt_state: PyTree,
           tx: optax.GradientTransformation,
           axis_names: Optional[AxisNames] = None, *,
           op: Optional[str] = None,
           backend: Optional[str] = None,
           compress: Optional[str] = None) -> Tuple[PyTree, PyTree]:
    """One ZeRO-1 step, for use INSIDE a shard_map'd train step.

    reduce_scatter the flat gradients over ``axis_names`` (selector-routed,
    same backends as :func:`gradsync.synchronize_gradients`), apply ``tx``
    on the local parameter/state shard, all_gather the updated shards back
    to the full replicated parameter pytree.  ``op`` defaults like
    synchronize_gradients: mean when ``config.gradsync_average``;
    ``compress="bf16"`` (default from ``config.gradsync_compress``) halves
    the gradient reduce_scatter's wire bytes exactly like the replicated
    path — the parameter all_gather stays full precision (it IS the new
    parameters).

    Returns ``(new_params, new_opt_state)`` — numerically identical to
    allreduce-then-update replicated DP (test_zero.py proves it against
    both that and the single-device oracle).
    """
    if axis_names is None:
        axis_names = tuple(runtime.current_mesh().axis_names)
    axes = _axes_tuple(axis_names)
    cfg = runtime.config() if runtime.is_initialized() else None
    if op is None:
        op = "mean" if (cfg is None or cfg.gradsync_average) else "sum"
    if op not in ("mean", "sum"):
        raise ValueError(f"zero.update op must be mean|sum, got {op!r}")
    if compress is None and cfg is not None:
        compress = cfg.gradsync_compress
    if compress not in (None, "none", "bf16"):
        raise ValueError(f"unknown gradient compression {compress!r}")

    n = _axis_size(axes)
    spec = _FlatSpec(params, int(n))
    g_flat = _flatten(grads, spec)
    if compress == "bf16":
        g_flat = g_flat.astype(jnp.bfloat16)
    g_shard = collectives.reduce_scatter_in_axis(g_flat, axes,
                                                 backend=backend)
    g_shard = g_shard.astype(spec.dtype)
    if op == "mean":
        g_shard = g_shard / n
    p_shard = lax.dynamic_slice(
        _flatten(params, spec), (_axis_index(axes) * spec.shard,),
        (spec.shard,))
    updates, new_state = tx.update(g_shard, opt_state, p_shard)
    p_shard = optax.apply_updates(p_shard, updates)
    p_flat = collectives.allgather_in_axis(p_shard, axes,
                                           backend=backend).reshape(-1)
    return _unflatten(p_flat, spec), new_state

"""ZeRO-1/ZeRO-3 data parallelism: shard optimizer state (and params) over
the DP axes.

Beyond-reference (TorchMPI is replicated-state DP only — SURVEY.md §3.3),
but it is the natural TPU-native evolution of the same allreduce step: the
allreduce decomposes into reduce_scatter + shard-local optimizer update +
all_gather (numerically identical to replicated DP), and the optimizer
state then only ever exists for each device's 1/n shard — an n-fold cut of
the largest replicated memory term after the params themselves.  On a
(dcn, ici) mesh the reduce_scatter/all_gather legs ride the same
selector-routed collectives as :func:`gradsync.synchronize_gradients`.

ZeRO-1 usage, inside a ``shard_map``-based train step (per-device code)::

    opt_state = zero.init(params, tx, axes, mesh=mesh)   # sharded state
    ...
    def step(params, opt_state, batch):
        grads = jax.grad(loss)(params, batch)
        params, opt_state = zero.update(params, grads, opt_state, tx, axes)
        ...

ZeRO-3 goes one level further: the PARAMETERS are also stored sharded
between steps (each device holds a flat 1/n shard); the step all-gathers
them transiently for forward+backward and reduce-scatters the gradients
back to shards — persistent memory for params AND optimizer state is 1/n,
with the full parameters existing only for the duration of a step::

    p_shard = zero.shard_params(params, axes, mesh=mesh)
    opt_state = zero.init(params, tx, axes, mesh=mesh)   # same state shape
    spec = zero.flat_spec(params, axes, mesh=mesh)       # static metadata
    ...
    def step(p_shard, opt_state, batch):                 # inside shard_map
        params = zero.gather_params(p_shard, spec, axes)
        grads = jax.grad(loss)(params, batch)
        p_shard, opt_state = zero.update3(p_shard, grads, opt_state, tx,
                                          axes, spec=spec)
        ...

End-to-end via ``recipes.make_bn_dp_train_step(..., zero=1)`` (state
sharded) or ``zero=3`` (state + params sharded), or annotation-driven FSDP
via ``recipes.make_fsdp_train_step`` (per-parameter GSPMD shardings — XLA
schedules the per-use gathers itself).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .. import collectives, fusion, planner, runtime
from .gradsync import _wire_compress

PyTree = Any
AxisNames = Union[str, Tuple[str, ...]]


def _axes_tuple(axis_names: AxisNames) -> Tuple[str, ...]:
    return ((axis_names,) if isinstance(axis_names, str)
            else tuple(axis_names))


def _axis_size(axes: Tuple[str, ...]) -> Any:
    n = 1
    for a in axes:
        n *= lax.axis_size(a)
    return n


def _axis_index(axes: Tuple[str, ...]):
    """Linearized device index over ``axes``, row-major in the given order —
    the same linearization ``lax.psum_scatter`` uses for tile assignment."""
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


# The flatten/pad/shard machinery is the fusion layer's FusedSpec — one
# definition shared with the fused in-axis collectives and the bucketed
# allreduce (torchmpi_tpu/fusion.py).  Construction goes through the
# planner's structure-keyed cache: every step build of the same
# parameter tree replays one FusedSpec instead of re-deriving the
# group/pad/shard layout per trace (torchmpi_tpu/planner.py).
_FlatSpec = fusion.FusedSpec


def _spec_for(tree, n_shards: int) -> fusion.FusedSpec:
    return planner.flat_spec_for(tree, int(n_shards))


def _local_shard(params: PyTree, spec: _FlatSpec,
                 axes: Tuple[str, ...]) -> jax.Array:
    """This device's flat extent of ``params`` — THE definition of the
    shard linearization (each dtype group's row-major
    :func:`_axis_index` extent, concatenated group-major, promoted to
    ``spec.dtype``), shared by :func:`init`, :func:`update`, and
    :func:`shard_params` so they can never disagree about which extent
    a device owns — and aligned with the per-dtype-group fused
    reduce_scatter legs, which deliver exactly these extents."""
    return fusion.local_shard(params, spec, _axis_index(axes))


def _resolve(axis_names: Optional[AxisNames], mesh: Optional[Mesh]
             ) -> Tuple[Mesh, Tuple[str, ...], int]:
    m = mesh if mesh is not None else runtime.current_mesh()
    axes = _axes_tuple(axis_names) if axis_names is not None \
        else tuple(m.axis_names)
    n = int(np.prod([m.shape[a] for a in axes]))
    return m, axes, n


def specs_like(state: PyTree, axis_names: AxisNames) -> PyTree:
    """PartitionSpec tree matching an existing ZeRO state pytree: the ONE
    definition of which leaves are sharded — per-parameter leaves
    (ndim >= 1) ``P(axes)``, scalar leaves (step counts) replicated.
    ``state`` may hold arrays or tracers (``jnp.ndim`` handles both), so
    step builders can call this on their traced inputs."""
    axes = _axes_tuple(axis_names)
    return jax.tree.map(
        lambda l: P(axes) if jnp.ndim(l) >= 1 else P(), state)


def state_specs(params: PyTree, tx: optax.GradientTransformation,
                axis_names: Optional[AxisNames] = None, *,
                mesh: Optional[Mesh] = None) -> PyTree:
    """PartitionSpec tree for the ZeRO-1 optimizer state: per-parameter
    leaves (ndim >= 1) sharded ``P(axes)``, scalar leaves (step counts)
    replicated.  Shared by :func:`init` and step builders that thread the
    state through their own shard_map."""
    m, axes, n = _resolve(axis_names, mesh)
    spec = _spec_for(params, n)
    shard_shape = jax.ShapeDtypeStruct((spec.shard,), spec.dtype)
    state_shapes = jax.eval_shape(tx.init, shard_shape)
    return specs_like(state_shapes, axes)


def init(params: PyTree, tx: optax.GradientTransformation,
         axis_names: Optional[AxisNames] = None, *,
         mesh: Optional[Mesh] = None) -> PyTree:
    """Build the optimizer state for ZeRO-1: state over each device's flat
    parameter shard, physically sharded across ``axis_names``.

    Runs its own jitted shard_map (init-time convenience, like
    ``synchronize_parameters``); the result feeds :func:`update` inside the
    train step.
    """
    m, axes, n = _resolve(axis_names, mesh)
    spec = _spec_for(params, n)
    specs = state_specs(params, tx, axes, mesh=m)

    def body(params):
        return tx.init(_local_shard(params, spec, axes))

    return jax.jit(shard_map(
        body, mesh=m, in_specs=P(), out_specs=specs,
        check_vma=False))(params)


def init_dcn_residuals(params: PyTree,
                       axis_names: Optional[AxisNames] = None, *,
                       mesh: Optional[Mesh] = None) -> Tuple[jax.Array, ...]:
    """Zero-initialized error-feedback residual state for the ZeRO
    gradient leg with a quantized DCN crossing (docs/HIERARCHICAL.md):
    one f32 accumulator per dtype group, shaped ``[n_devices, padded /
    ici_n]`` — the group's ICI-scattered intermediate, where the
    quantization happens.  Thread it through the step sharded
    ``P(axes)`` on the leading axis, like the optimizer state."""
    from .. import compress as _codec

    m, axes, n = _resolve(axis_names, mesh)
    _codec.ef_axes(axes)
    n_inner = int(m.shape[axes[1]])
    spec = _spec_for(params, n)
    return tuple(_codec.init_residuals(
        _codec.expected_shards([g.padded for g in spec.groups],
                               n_inner), n))


def update(params: PyTree, grads: PyTree, opt_state: PyTree,
           tx: optax.GradientTransformation,
           axis_names: Optional[AxisNames] = None, *,
           op: Optional[str] = None,
           backend: Optional[str] = None,
           compress: Optional[str] = None,
           presynced: bool = False,
           dcn_residuals=None,
           dcn_compress: Optional[str] = None):
    """One ZeRO-1 step, for use INSIDE a shard_map'd train step.

    reduce_scatter the flat gradients over ``axis_names`` (selector-routed,
    same backends as :func:`gradsync.synchronize_gradients`), apply ``tx``
    on the local parameter/state shard, all_gather the updated shards back
    to the full replicated parameter pytree.  ``op`` defaults like
    synchronize_gradients: mean when ``config.gradsync_average``;
    ``compress="bf16"`` (default from ``config.gradsync_compress``) halves
    the gradient reduce_scatter's wire bytes exactly like the replicated
    path — the parameter all_gather stays full precision (it IS the new
    parameters).

    Returns ``(new_params, new_opt_state)`` — numerically identical to
    allreduce-then-update replicated DP (test_zero.py proves it against
    both that and the single-device oracle).

    ``presynced=True`` is the backprop-overlap mode (docs/OVERLAP.md):
    ``grads`` are ALREADY reduced across ``axis_names`` (by
    ``gradsync.make_overlapped_grad_fn``, op/compress applied there),
    so the reduce_scatter leg is replaced by a local slice of this
    device's shard — the communication already happened, overlapped
    under the backward pass.

    ``dcn_residuals`` (state from :func:`init_dcn_residuals`) switches
    the gradient leg to the **error-feedback quantized DCN path** on a
    two-level mesh (docs/HIERARCHICAL.md): reduce_scatter over ICI in
    each group's native dtype, the small shard crossing DCN quantized
    with ``dcn_compress`` (default ``config.dcn_compress``), the new
    quantization error returned as next step's state — the return then
    becomes ``(new_params, new_opt_state, new_residuals)``.  On this
    path an explicit ``compress=`` raises (the DCN codec IS the wire
    compression) and ``backend=`` routes only the parameter
    all_gather — the gradient leg is the fixed two-level schedule.
    """
    if axis_names is None:
        axis_names = tuple(runtime.current_mesh().axis_names)
    axes = _axes_tuple(axis_names)
    new_res = None
    if presynced:
        spec = _spec_for(params, int(_axis_size(axes)))
        g_shard = _local_shard(grads, spec, axes)
        # Presynced grads already communicated (EF, if any, happened in
        # the overlap schedule, which owns its own residual state) —
        # hand the zero-leg residuals back unchanged instead of
        # clobbering the caller's state with None.
        new_res = dcn_residuals
    else:
        g_shard, spec, new_res = _reduce_scatter_grads(
            grads, axes, spec=None, params=params, op=op,
            backend=backend, compress=compress,
            dcn_residuals=dcn_residuals, dcn_compress=dcn_compress)
    p_shard = _local_shard(params, spec, axes)
    updates, new_state = tx.update(g_shard, opt_state, p_shard)
    p_shard = optax.apply_updates(p_shard, updates)
    p_flat = collectives.allgather_in_axis(p_shard, axes,
                                           backend=backend).reshape(-1)
    new_params = fusion.unflatten_shards(p_flat, spec)
    if dcn_residuals is not None:
        return new_params, new_state, new_res
    return new_params, new_state


def _reduce_scatter_grads(grads: PyTree, axes: Tuple[str, ...], *,
                          spec: Optional[_FlatSpec],
                          params: Optional[PyTree],
                          op: Optional[str],
                          backend: Optional[str],
                          compress: Optional[str],
                          dcn_residuals=None,
                          dcn_compress: Optional[str] = None
                          ) -> Tuple[jax.Array, _FlatSpec, Optional[tuple]]:
    """The shared ZeRO gradient leg (ZeRO-1 :func:`update` and ZeRO-3
    :func:`update3`): resolve op/compress defaults from config (validated
    BEFORE any axis/tracing use, so bad arguments raise eagerly outside
    shard_map too), flatten, optionally bf16-compress the wire,
    reduce_scatter over ``axes``, restore dtype, apply mean scaling.
    Pass either a prebuilt ``spec`` (ZeRO-3) or ``params`` to derive one
    (ZeRO-1).  Returns ``(flat gradient shard, spec, new_residuals)``
    — ``new_residuals`` is None unless the error-feedback DCN path ran
    (``dcn_residuals`` given on a two-level span)."""
    cfg = runtime.config() if runtime.is_initialized() else None
    if op is None:
        op = "mean" if (cfg is None or cfg.gradsync_average) else "sum"
    if op not in ("mean", "sum"):
        raise ValueError(f"zero update op must be mean|sum, got {op!r}")
    explicit_compress = compress is not None
    if compress is None and cfg is not None:
        compress = cfg.gradsync_compress
    compress = _wire_compress(compress, site="zero update")
    codec = None
    if dcn_residuals is not None:
        from .. import compress as _codec

        # One shared activation gate (compress.resolve_ef): codec
        # required, explicit compress= raises rather than being
        # silently dropped.  ``backend=`` stays legal here
        # (allow_backend) — it still routes the parameter all_gather,
        # while the gradient leg is the fixed two-level schedule.
        codec = _codec.resolve_ef(
            dcn_compress, cfg, site="zero update", backend=backend,
            explicit_compress=explicit_compress, compress=compress,
            allow_backend=True)
        _codec.ef_axes(axes)

    n = _axis_size(axes)
    if spec is None:
        spec = _spec_for(params, int(n))
    if cfg is not None and cfg.obs != "off":
        from .. import obs

        obs.record_zero("reduce_scatter", len(spec.groups),
                        int(spec.n_shards))
    # Trace-time layout record for the static analyzer (rule C1): the
    # shard layout the spec was built for vs the axes this call actually
    # spans.  A stale spec (wrong n_shards) silently pairs every device
    # with the wrong parameter extent — exactly what C1 exists to catch.
    if fusion._trace_listener is not None:
        fusion._emit_trace_record(dict(
            kind="zero_reduce_scatter", axes=tuple(axes),
            source=fusion._record_source(),
            n_shards=int(spec.n_shards), axis_size=int(n),
            groups=[(np.dtype(g.dtype).name, int(g.padded), int(g.shard))
                    for g in spec.groups]))
    # One reduce_scatter per dtype group, each in its NATIVE dtype (the
    # old promoted concat upcast every bf16 leaf to the tree's
    # result_type on the wire); the group shards then promote to
    # spec.dtype and concatenate — exactly the _local_shard
    # linearization, so the optimizer pairs them with the right
    # parameter extents.  ``compress="bf16"`` still narrows wider
    # groups on top.
    g_leaves = jax.tree.leaves(grads)
    new_res = None
    ef_inputs = None  # pre-step residuals (the guard's revert fallback)
    if codec is not None and int(_axis_size(axes[:1])) > 1:
        # Error-feedback quantized DCN path: reduce_scatter over ICI in
        # each group's native dtype, residual-corrected quantized
        # crossing over DCN, pre-permuted so every device still lands
        # on its dcn-major _local_shard extent
        # (compress.ef_group_reduce_scatter — docs/HIERARCHICAL.md).
        from .. import compress as _codec_mod

        n_i = int(_axis_size(axes[1:]))
        want = _codec_mod.expected_shards(
            [g.padded for g in spec.groups], n_i)
        res_list = _codec_mod.check_residuals(
            dcn_residuals, want, axes, site="zero update",
            layout="the dtype-group bucket layout",
            init_hint="zero.init_dcn_residuals(params, ...) from the "
                      "SAME params/axes")
        from . import hierarchical

        min_bytes = (cfg.dcn_compress_min_bytes if cfg is not None else 0)
        serialize = (len(spec.groups) > 1
                     and hierarchical._serialize_collectives())
        parts, new_parts = [], []
        prev = None
        for g, r in zip(spec.groups, res_list):
            g_flat = fusion.group_flat(g_leaves, g, pad=True)
            if serialize and prev is not None:
                # Unordered sibling psum_scatter/allreduce chains
                # deadlock the CPU sim's blocking rendezvous (see
                # hierarchical._serialize_collectives) — chain group
                # i's input on group i-1's shard there.
                g_flat, _ = lax.optimization_barrier((g_flat, prev))
            shard, nr = _codec_mod.ef_group_reduce_scatter(
                g_flat, axes[0], axes[1], codec, r,
                min_bytes=min_bytes)
            prev = shard
            parts.append(shard.astype(spec.dtype))
            new_parts.append(nr)
        new_res = tuple(new_parts)
        ef_inputs = tuple(res_list)
    else:
        if codec is not None:
            # Flat span: no DCN crossing — plain path, residuals
            # unchanged.
            from .. import selector as _sel

            _sel._note_fallback("reduce_scatter", "dcn-" + codec,
                                "flat mesh (n_dcn <= 1)",
                                target="the plain reduce_scatter leg")
            new_res = tuple(dcn_residuals) \
                if isinstance(dcn_residuals, (list, tuple)) \
                else dcn_residuals
        parts = []
        for g in spec.groups:
            g_flat = fusion.group_flat(g_leaves, g, pad=True)
            if compress == "bf16":
                g_flat = g_flat.astype(jnp.bfloat16)
            shard = collectives.reduce_scatter_in_axis(g_flat, axes,
                                                       backend=backend)
            parts.append(shard.astype(spec.dtype))
    g_shard = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    if op == "mean":
        g_shard = g_shard / n
    if cfg is not None and cfg.guard in ("numeric", "full"):
        # Numeric tripwire on the synced gradient shard (docs/GUARD.md):
        # one fused sum-of-squares over this device's extent — each
        # shard leg checks exactly the update it will apply.  Trace-time
        # gate; guard="off" adds zero branches to the compiled step.
        # On the EF path the residuals revert to the pre-step state
        # under the same verdict (code review: a tripped round's error
        # mass must not ride the accumulator into the next step).
        from .. import guard

        if ef_inputs is not None:
            g_shard, reverted = guard.check_flat(
                g_shard, site="zero",
                aux=list(zip(new_res, ef_inputs)))
            new_res = tuple(reverted)
        else:
            g_shard = guard.check_flat(g_shard, site="zero")
    return g_shard, spec, new_res


# --------------------------------------------------------------------------
# ZeRO-3: parameters sharded between steps as well.


def flat_spec(params: PyTree, axis_names: Optional[AxisNames] = None, *,
              mesh: Optional[Mesh] = None) -> _FlatSpec:
    """Static flatten/shard metadata for ``params`` over ``axis_names`` —
    the one object :func:`gather_params` / :func:`update3` need to map
    between the flat shard and the structured pytree.  Build it OUTSIDE
    jit from the real (or eval_shape'd) parameter pytree."""
    _, _, n = _resolve(axis_names, mesh)
    return _spec_for(params, n)


def shard_params(params: PyTree, axis_names: Optional[AxisNames] = None, *,
                 mesh: Optional[Mesh] = None) -> jax.Array:
    """Slice a replicated parameter pytree down to this device's flat
    ZeRO-3 shard ``[shard]``, physically sharded ``P(axes)`` across the
    mesh.  Init-time convenience (runs its own jitted shard_map), like
    :func:`init`."""
    m, axes, _ = _resolve(axis_names, mesh)
    spec = flat_spec(params, axes, mesh=m)

    def body(params):
        return _local_shard(params, spec, axes)

    return jax.jit(shard_map(
        body, mesh=m, in_specs=P(), out_specs=P(axes),
        check_vma=False))(params)


def gather_params(p_shard: jax.Array, spec: _FlatSpec,
                  axis_names: AxisNames, *,
                  backend: Optional[str] = None) -> PyTree:
    """All-gather the flat ZeRO-3 shards into the full parameter pytree —
    the transient materialization at the top of a step.  For use INSIDE a
    shard_map'd step; selector-routed like every other collective."""
    axes = _axes_tuple(axis_names)
    flat = collectives.allgather_in_axis(p_shard, axes,
                                         backend=backend).reshape(-1)
    return fusion.unflatten_shards(flat, spec)


def update3(p_shard: jax.Array, grads: PyTree, opt_state: PyTree,
            tx: optax.GradientTransformation,
            axis_names: AxisNames, *, spec: _FlatSpec,
            op: Optional[str] = None,
            backend: Optional[str] = None,
            compress: Optional[str] = None,
            presynced: bool = False,
            dcn_residuals=None,
            dcn_compress: Optional[str] = None):
    """One ZeRO-3 step, for use INSIDE a shard_map'd train step.

    reduce_scatter the flat gradients over ``axis_names``, apply ``tx`` on
    the local shard, and return the updated FLAT SHARD — unlike
    :func:`update` there is no trailing all_gather: the parameters stay
    sharded until the next step's :func:`gather_params`.  Defaults
    (``op``/``compress``) follow :func:`update`.

    Returns ``(new_p_shard, new_opt_state)`` — numerically identical to
    allreduce-then-update replicated DP (test_zero.py proves it).

    ``presynced=True`` as in :func:`update`: ``grads`` arrived already
    reduced (the overlap schedule) and this device slices its shard
    locally instead of re-communicating.  ``dcn_residuals`` as in
    :func:`update`: the error-feedback quantized DCN leg, returning
    ``(new_p_shard, new_opt_state, new_residuals)``.
    """
    axes = _axes_tuple(axis_names)
    new_res = None
    if presynced:
        g_shard = _local_shard(grads, spec, axes)
        # Same passthrough as :func:`update`: presynced EF state lives
        # in the overlap schedule, not this leg.
        new_res = dcn_residuals
    else:
        g_shard, _, new_res = _reduce_scatter_grads(
            grads, axes, spec=spec, params=None, op=op, backend=backend,
            compress=compress, dcn_residuals=dcn_residuals,
            dcn_compress=dcn_compress)
    updates, new_state = tx.update(g_shard, opt_state, p_shard)
    new_shard = optax.apply_updates(p_shard, updates)
    if dcn_residuals is not None:
        return new_shard, new_state, new_res
    return new_shard, new_state


def unshard_params(p_shard: jax.Array, params_template: PyTree,
                   axis_names: Optional[AxisNames] = None, *,
                   mesh: Optional[Mesh] = None) -> PyTree:
    """Reassemble the full replicated parameter pytree from ZeRO-3 shards
    (checkpoint export / eval).  Init-time convenience mirror of
    :func:`shard_params`."""
    m, axes, _ = _resolve(axis_names, mesh)
    spec = flat_spec(params_template, axes, mesh=m)

    def body(p_shard):
        return gather_params(p_shard, spec, axes)

    return jax.jit(shard_map(
        body, mesh=m, in_specs=P(axes),
        out_specs=jax.tree.map(lambda _: P(), params_template),
        check_vma=False))(p_shard)

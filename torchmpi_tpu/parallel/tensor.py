"""Tensor (model) parallelism building blocks.

The reference implements data parallelism only (SURVEY.md §3.3 — TP/PP are
explicitly out of its scope), but requires the communicator design not to
preclude additional mesh axes (§6.7).  This module exercises that guarantee
with the two canonical TP layers (Megatron-style), built on the same in-axis
collectives as everything else:

- :func:`column_parallel_dense` — weight sharded on the OUTPUT feature dim;
  no communication forward (each device computes its feature slice), psum in
  backward (handled by autodiff's transpose of the replicated input).
- :func:`row_parallel_dense` — weight sharded on the INPUT feature dim;
  forward ends with a psum over the axis (the classic f/g pair).

A column-parallel layer followed by a row-parallel layer (the transformer
MLP pattern) costs exactly one allreduce forward and one backward.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .. import collectives


# The Megatron f/g conjugate pair.  JAX's native transpose of psum is psum,
# which double-counts when inputs/cotangents are replicated across the model
# axis; these custom VJPs pin the intended semantics:
#   g: forward allreduce, backward identity   (end of a row-parallel layer)
#   f: forward identity,  backward allreduce  (entry of a column-parallel
#                                              layer, for exact input grads)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def g_allreduce(x, axis_name, backend=None):
    return collectives.allreduce_in_axis(x, axis_name, op="sum",
                                         backend=backend)


def _g_fwd(x, axis_name, backend):
    return g_allreduce(x, axis_name, backend), None


def _g_bwd(axis_name, backend, _, cot):
    return (cot,)


g_allreduce.defvjp(_g_fwd, _g_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def f_identity(x, axis_name, backend=None):
    return x


def _f_fwd(x, axis_name, backend):
    return x, None


def _f_bwd(axis_name, backend, _, cot):
    return (collectives.allreduce_in_axis(cot, axis_name, op="sum",
                                          backend=backend),)


f_identity.defvjp(_f_fwd, _f_bwd)


def column_parallel_dense(x, w_local, axis_name: str,
                          b_local: Optional[jnp.ndarray] = None):
    """x: [..., d_in] replicated over ``axis_name``; w_local: [d_in,
    d_out/n] this device's column block.  Returns [..., d_out/n] — the local
    slice of the activations (gather only if you must materialize)."""
    y = f_identity(x, axis_name) @ w_local
    if b_local is not None:
        y = y + b_local
    return y


def row_parallel_dense(x_local, w_local, axis_name: str,
                       b: Optional[jnp.ndarray] = None,
                       backend: Optional[str] = None):
    """x_local: [..., d_in/n] (e.g. the output of a column-parallel layer);
    w_local: [d_in/n, d_out] this device's row block.  The partial products
    are summed over the axis — the one collective of the f/g pair."""
    part = x_local @ w_local
    y = g_allreduce(part, axis_name, backend)
    if b is not None:
        y = y + b
    return y


def tp_mlp(x, w1_local, w2_local, axis_name: str, act=jnp.tanh,
           backend: Optional[str] = None):
    """Column -> activation -> row: the Megatron MLP block, one allreduce."""
    h = act(column_parallel_dense(x, w1_local, axis_name))
    return row_parallel_dense(h, w2_local, axis_name, backend=backend)


def tp_attention(x, wq_local, wk_local, wv_local, wo_local,
                 axis_name: str, *, num_heads: int, causal: bool = True,
                 backend: Optional[str] = None, impl: str = "dense",
                 window: Optional[int] = None):
    """Megatron-style tensor-parallel multi-head self-attention: the heads
    shard over ``axis_name``.

    ``x``: [B, T, D] replicated.  ``wq/wk/wv_local``: [D, Hl*Dh] column
    blocks (this device's Hl = num_heads/n heads, head-major columns — a
    :func:`shard_columns` slice of the full projection).  ``wo_local``:
    [Hl*Dh, D] row block.  ``num_heads`` is the GLOBAL head count (the
    per-head width is not recoverable from the local shapes alone: the
    local width is D/n for every valid head split).  Each device runs its
    heads end-to-end — scores, softmax, and the value contraction never
    cross devices — and the output projection's partial products sum over
    the axis: exactly one allreduce forward (``g``) and one backward
    (``f``), the same cost profile as :func:`tp_mlp`.

    ``impl``: ``"dense"`` materializes the [B, Hl, T, T] score matrix —
    fine at short T, O(T^2) memory (ADVICE r3).  ``"flash"`` runs this
    device's heads through the Pallas blocked flash kernel
    (``ops/flash.py``) instead — O(T * block) memory, composes with the
    long-context stack, and accepts ``window`` for sliding-window
    attention; the TP collective structure is identical either way
    (the kernel is per-device, head-local).
    """
    B, T, _ = x.shape
    n = lax.axis_size(axis_name)
    if num_heads % n:
        raise ValueError(f"num_heads {num_heads} must divide by the "
                         f"axis size {n}")
    h_local = num_heads // n
    width = wq_local.shape[-1]
    if width % h_local:
        raise ValueError(f"local qkv width {width} must divide by local "
                         f"head count {h_local}")
    d_head = width // h_local

    if impl not in ("dense", "flash"):
        raise ValueError(f"impl must be 'dense' or 'flash', got {impl!r}")
    if impl == "dense" and window is not None:
        raise ValueError("window= requires impl='flash'")

    xr = f_identity(x, axis_name)
    q = (xr @ wq_local).reshape(B, T, h_local, d_head)
    k = (xr @ wk_local).reshape(B, T, h_local, d_head)
    v = (xr @ wv_local).reshape(B, T, h_local, d_head)
    if impl == "flash":
        from ..ops.flash import flash_attention_grad

        ctx = flash_attention_grad(q, k, v, causal=causal,
                                   window=window).reshape(B, T, width)
    else:
        scores = jnp.einsum("bthd,bshd->bhts", q, k) / jnp.sqrt(
            jnp.float32(d_head)).astype(x.dtype)
        if causal:
            mask = jnp.tril(jnp.ones((T, T), bool))
            scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(x.dtype)
        ctx = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(B, T, width)
    return row_parallel_dense(ctx, wo_local, axis_name, backend=backend)


def tp_transformer_block(x, p_local, axis_name: str, *, num_heads: int,
                         causal: bool = True,
                         backend: Optional[str] = None,
                         attn_impl: str = "dense",
                         window: Optional[int] = None):
    """A full pre-LN transformer block with BOTH sublayers tensor-parallel:
    ``x + tp_attention(LN(x))`` then ``x + tp_mlp(LN(x))`` — two
    allreduces forward (one per sublayer), the canonical Megatron block.

    ``p_local``: dict with ``ln1/ln2`` (scale, bias — replicated),
    ``wq/wk/wv/wo`` (attention blocks as in :func:`tp_attention`), and
    ``w1/w2`` (MLP blocks as in :func:`tp_mlp`).  ``attn_impl="flash"``
    routes the attention sublayer through the Pallas flash kernel for
    long-context TP training (O(T*block) memory; ``window`` supported).
    """
    def ln(h, scale, bias):
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        return (h - mu) * lax.rsqrt(var + 1e-6) * scale + bias

    a = tp_attention(ln(x, *p_local["ln1"]), p_local["wq"], p_local["wk"],
                     p_local["wv"], p_local["wo"], axis_name,
                     num_heads=num_heads, causal=causal, backend=backend,
                     impl=attn_impl, window=window)
    x = x + a
    m = tp_mlp(ln(x, *p_local["ln2"]), p_local["w1"], p_local["w2"],
               axis_name, act=partial(jax.nn.gelu, approximate=False),
               backend=backend)
    return x + m


def shard_columns(w, axis_name: str, n: int, index):
    """Static helper: slice a full [d_in, d_out] weight into this device's
    column block (used at setup time, outside jit, via numpy)."""
    cols = w.shape[1] // n
    return w[:, index * cols:(index + 1) * cols]


def shard_rows(w, axis_name: str, n: int, index):
    rows = w.shape[0] // n
    return w[index * rows:(index + 1) * rows, :]

"""Tensor (model) parallelism building blocks.

The reference implements data parallelism only (SURVEY.md §3.3 — TP/PP are
explicitly out of its scope), but requires the communicator design not to
preclude additional mesh axes (§6.7).  This module exercises that guarantee
with the two canonical TP layers (Megatron-style), built on the same in-axis
collectives as everything else:

- :func:`column_parallel_dense` — weight sharded on the OUTPUT feature dim;
  no communication forward (each device computes its feature slice), psum in
  backward (handled by autodiff's transpose of the replicated input).
- :func:`row_parallel_dense` — weight sharded on the INPUT feature dim;
  forward ends with a psum over the axis (the classic f/g pair).

A column-parallel layer followed by a row-parallel layer (the transformer
MLP pattern) costs exactly one allreduce forward and one backward.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .. import collectives


# The Megatron f/g conjugate pair.  JAX's native transpose of psum is psum,
# which double-counts when inputs/cotangents are replicated across the model
# axis; these custom VJPs pin the intended semantics:
#   g: forward allreduce, backward identity   (end of a row-parallel layer)
#   f: forward identity,  backward allreduce  (entry of a column-parallel
#                                              layer, for exact input grads)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def g_allreduce(x, axis_name, backend=None):
    return collectives.allreduce_in_axis(x, axis_name, op="sum",
                                         backend=backend)


def _g_fwd(x, axis_name, backend):
    return g_allreduce(x, axis_name, backend), None


def _g_bwd(axis_name, backend, _, cot):
    return (cot,)


g_allreduce.defvjp(_g_fwd, _g_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def f_identity(x, axis_name, backend=None):
    return x


def _f_fwd(x, axis_name, backend):
    return x, None


def _f_bwd(axis_name, backend, _, cot):
    return (collectives.allreduce_in_axis(cot, axis_name, op="sum",
                                          backend=backend),)


f_identity.defvjp(_f_fwd, _f_bwd)


def column_parallel_dense(x, w_local, axis_name: str,
                          b_local: Optional[jnp.ndarray] = None):
    """x: [..., d_in] replicated over ``axis_name``; w_local: [d_in,
    d_out/n] this device's column block.  Returns [..., d_out/n] — the local
    slice of the activations (gather only if you must materialize)."""
    y = f_identity(x, axis_name) @ w_local
    if b_local is not None:
        y = y + b_local
    return y


def row_parallel_dense(x_local, w_local, axis_name: str,
                       b: Optional[jnp.ndarray] = None,
                       backend: Optional[str] = None):
    """x_local: [..., d_in/n] (e.g. the output of a column-parallel layer);
    w_local: [d_in/n, d_out] this device's row block.  The partial products
    are summed over the axis — the one collective of the f/g pair."""
    part = x_local @ w_local
    y = g_allreduce(part, axis_name, backend)
    if b is not None:
        y = y + b
    return y


def tp_mlp(x, w1_local, w2_local, axis_name: str, act=jnp.tanh,
           backend: Optional[str] = None):
    """Column -> activation -> row: the Megatron MLP block, one allreduce."""
    h = act(column_parallel_dense(x, w1_local, axis_name))
    return row_parallel_dense(h, w2_local, axis_name, backend=backend)


def shard_columns(w, axis_name: str, n: int, index):
    """Static helper: slice a full [d_in, d_out] weight into this device's
    column block (used at setup time, outside jit, via numpy)."""
    cols = w.shape[1] // n
    return w[:, index * cols:(index + 1) * cols]


def shard_rows(w, axis_name: str, n: int, index):
    rows = w.shape[0] // n
    return w[index * rows:(index + 1) * rows, :]

"""Expert parallelism: all-to-all Mixture-of-Experts dispatch.

Not in the reference (SURVEY.md §3.3: EP out of its scope, like TP/PP/SP);
this completes the parallelism-strategy set on the same communicator tree.
Minimal, correct, capacity-based top-1 MoE:

- every device holds ``experts_per_device`` experts (the expert dimension is
  sharded over ``axis_name``);
- tokens are routed by a gating projection, packed into per-expert capacity
  buffers (static shapes — XLA-friendly; overflow tokens drop, the standard
  capacity-factor trade), exchanged with ONE ``all_to_all``, processed by
  the local experts, and returned by the inverse ``all_to_all``;
- combine scales by the gate probability, so dropped tokens degrade
  gracefully to zero contribution (residual connections carry them).

The communication pattern (dispatch all-to-all, combine all-to-all) is the
EP analog of the reference's allreduce: one collective pair per MoE layer.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def top1_dispatch(x, gate_logits, n_experts_global: int, capacity: int):
    """Pack tokens into per-expert capacity slots (single device's view).

    x: [T, D]; gate_logits: [T, E_global].
    Returns (buffers [E_global, capacity, D], combine_w [T], expert_of [T],
    slot_of [T], valid [T]).
    """
    T, D = x.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)
    expert_of = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.take_along_axis(probs, expert_of[:, None], axis=1)[:, 0]
    # Position of each token within its expert's queue.
    onehot = jax.nn.one_hot(expert_of, n_experts_global, dtype=jnp.int32)
    pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1)  # [T, E]
    slot_of = jnp.take_along_axis(pos_in_expert, expert_of[:, None],
                                  axis=1)[:, 0]
    valid = slot_of < capacity
    buffers = jnp.zeros((n_experts_global, capacity, D), x.dtype)
    safe_slot = jnp.where(valid, slot_of, capacity - 1)
    # scatter-ADD, not set: overflow tokens (clamped to the last slot)
    # contribute zeros instead of clobbering the slot's real occupant.
    buffers = buffers.at[expert_of, safe_slot].add(
        jnp.where(valid[:, None], x, 0.0))
    return buffers, gate, expert_of, slot_of, valid


def moe_layer(x, gate_w, expert_fn: Callable, expert_params,
              axis_name: str, *, capacity_factor: float = 2.0):
    """Top-1 expert-parallel MoE layer, for use inside shard_map.

    x: [T, D] this device's tokens; gate_w: [D, E_global] replicated;
    expert_params: this device's experts, leaves shaped
    ``[experts_per_device, ...]``; ``expert_fn(params_e, tokens) -> tokens``
    applies ONE expert.  Returns [T, D].
    """
    n_dev = lax.axis_size(axis_name)
    T, D = x.shape
    e_local = jax.tree.leaves(expert_params)[0].shape[0]
    E = n_dev * e_local
    capacity = max(1, int(capacity_factor * T / E))

    gate_logits = x @ gate_w
    buffers, gate, expert_of, slot_of, valid = top1_dispatch(
        x, gate_logits, E, capacity)

    # Dispatch: buffers [E, C, D] with E = n_dev * e_local, expert-major.
    # tiled all_to_all on axis 0 sends block d (rows d*e_local:(d+1)*e_local)
    # to device d; the receive concatenates source blocks in order, so
    # dispatched[s*e_local + j] = source s's buffer for my local expert j.
    dispatched = lax.all_to_all(buffers, axis_name, split_axis=0,
                                concat_axis=0, tiled=True)
    # Per-local-expert queues: [e_local, n_dev * C, D].
    queues = (dispatched.reshape(n_dev, e_local, capacity, D)
              .transpose(1, 0, 2, 3).reshape(e_local, n_dev * capacity, D))

    # Apply local experts (vmapped over the expert dim).
    processed = jax.vmap(expert_fn)(expert_params, queues)

    # Combine: inverse exchange — repack expert-major and all_to_all back,
    # landing in the original [E, C, D] layout on each source device.
    packed = (processed.reshape(e_local, n_dev, capacity, D)
              .transpose(1, 0, 2, 3).reshape(E, capacity, D))
    returned = lax.all_to_all(packed, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)

    out = returned[expert_of, jnp.where(valid, slot_of, 0)]
    out = jnp.where(valid[:, None], out, 0.0) * gate[:, None]
    return out

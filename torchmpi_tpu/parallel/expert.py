"""Expert parallelism: all-to-all Mixture-of-Experts dispatch.

Not in the reference (SURVEY.md §3.3: EP out of its scope, like TP/PP/SP);
this completes the parallelism-strategy set on the same communicator tree.
Minimal, correct, capacity-based top-k MoE (k=1 Switch-style combine,
k>=2 GShard-style renormalized combine):

- every device holds ``experts_per_device`` experts (the expert dimension is
  sharded over ``axis_name``);
- tokens are routed by a gating projection, packed into per-expert capacity
  buffers (static shapes — XLA-friendly; overflow tokens drop, the standard
  capacity-factor trade), exchanged with ONE ``all_to_all``, processed by
  the local experts, and returned by the inverse ``all_to_all``;
- combine scales by the gate probability, so dropped tokens degrade
  gracefully to zero contribution (residual connections carry them).

The communication pattern (dispatch all-to-all, combine all-to-all) is the
EP analog of the reference's allreduce: one collective pair per MoE layer.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def topk_dispatch(x, gate_logits, n_experts_global: int, capacity: int,
                  k: int, *, renormalize: bool = True, probs=None):
    """Pack tokens into per-expert capacity slots along their top-k routes.

    x: [T, D]; gate_logits: [T, E_global].  Slots fill RANK-MAJOR
    (GShard priority): every token's rank-0 choice claims a slot before
    any token's rank-1 choice does, so under overflow an expert drops
    tokens' secondary routes first — never a later token's primary route
    in favor of an earlier token's secondary one.  Combine weights: the
    top-k probabilities renormalized over the selected experts
    (``renormalize=True``, GShard) or raw (False — at k=1 that is
    Switch-style scaling by the top-1 probability).

    Returns (buffers [E_global, capacity, D], combine_w [T, k],
    expert_of [T, k], slot_of [T, k], valid [T, k]).
    """
    T, D = x.shape
    if probs is None:
        probs = jax.nn.softmax(gate_logits, axis=-1)
    topk_p, topk_e = lax.top_k(probs, k)  # [T, k]
    combine_w = (topk_p / jnp.maximum(
        topk_p.sum(axis=-1, keepdims=True), 1e-9)
        if renormalize else topk_p)
    # Rank-major route order: [k*T] with all rank-0 routes first, so the
    # running per-expert cumsum assigns slots to every primary route
    # before any secondary route competes for one.
    routes = topk_e.T.reshape(-1)
    onehot = jax.nn.one_hot(routes, n_experts_global, dtype=jnp.int32)
    pos_in_expert = jnp.cumsum(onehot, axis=0) - 1
    slot_flat = jnp.take_along_axis(pos_in_expert, routes[:, None],
                                    axis=1)[:, 0]
    slot_of = slot_flat.reshape(k, T).T  # back to [T, k]
    valid = slot_of < capacity
    buffers = jnp.zeros((n_experts_global, capacity, D), x.dtype)
    safe_slot = jnp.where(valid, slot_of, capacity - 1)
    x_routes = jnp.broadcast_to(x[:, None], (T, k, D))
    # scatter-ADD, not set: overflow routes (clamped to the last slot)
    # contribute zeros instead of clobbering the slot's real occupant.
    buffers = buffers.at[topk_e, safe_slot].add(
        jnp.where(valid[..., None], x_routes, 0.0))
    return buffers, combine_w, topk_e, slot_of, valid


def load_balance_loss(gate_logits, expert_of, n_experts: int, *,
                      probs=None):
    """Switch-transformer auxiliary load-balancing loss for one device's
    tokens: ``E * sum_e(f_e * P_e)`` with ``f_e`` the fraction of routes
    dispatched to expert e and ``P_e`` the mean router probability.
    Equals 1.0 under perfectly uniform routing; grows as routing
    collapses.  ``expert_of``: [T] or [T, k] selected experts (from
    :func:`topk_dispatch`).  Pass ``probs`` if the router softmax is
    already computed.  Scale (typ. 1e-2) and add to the task loss.
    """
    if probs is None:
        probs = jax.nn.softmax(gate_logits, axis=-1)
    P = probs.mean(axis=0)  # [E]
    if expert_of.ndim == 1:
        expert_of = expert_of[:, None]
    f = jax.nn.one_hot(expert_of.reshape(-1), n_experts).mean(axis=0)
    return n_experts * jnp.sum(f * P)


def moe_layer(x, gate_w, expert_fn: Callable, expert_params,
              axis_name: str, *, capacity_factor: float = 2.0, k: int = 1,
              return_aux: bool = False):
    """Top-k expert-parallel MoE layer, for use inside shard_map.

    x: [T, D] this device's tokens; gate_w: [D, E_global] replicated;
    expert_params: this device's experts, leaves shaped
    ``[experts_per_device, ...]``; ``expert_fn(params_e, tokens) -> tokens``
    applies ONE expert.  Returns [T, D].

    ``k=1`` keeps Switch-style combine (scale by the raw top-1
    probability); ``k>=2`` is GShard-style — contributions weighted by the
    top-k probabilities renormalized over the selected experts.  Capacity
    scales with k: ``capacity_factor * T * k / E`` slots per expert.
    ``return_aux=True`` additionally returns this device's
    :func:`load_balance_loss` (add it to the task loss, typ. scaled 1e-2,
    to keep routing from collapsing onto few experts).
    """
    if k < 1:
        raise ValueError(f"moe_layer needs k >= 1 experts per token, "
                         f"got {k}")
    n_dev = lax.axis_size(axis_name)
    T, D = x.shape
    e_local = jax.tree.leaves(expert_params)[0].shape[0]
    E = n_dev * e_local
    capacity = max(1, int(capacity_factor * T * k / E))

    gate_logits = x @ gate_w
    probs = jax.nn.softmax(gate_logits, axis=-1)  # shared with the aux loss
    buffers, gate, expert_of, slot_of, valid = topk_dispatch(
        x, gate_logits, E, capacity, k, renormalize=k > 1, probs=probs)

    # Dispatch: buffers [E, C, D] with E = n_dev * e_local, expert-major.
    # tiled all_to_all on axis 0 sends block d (rows d*e_local:(d+1)*e_local)
    # to device d; the receive concatenates source blocks in order, so
    # dispatched[s*e_local + j] = source s's buffer for my local expert j.
    dispatched = lax.all_to_all(buffers, axis_name, split_axis=0,
                                concat_axis=0, tiled=True)
    # Per-local-expert queues: [e_local, n_dev * C, D].
    queues = (dispatched.reshape(n_dev, e_local, capacity, D)
              .transpose(1, 0, 2, 3).reshape(e_local, n_dev * capacity, D))

    # Apply local experts (vmapped over the expert dim).
    processed = jax.vmap(expert_fn)(expert_params, queues)

    # Combine: inverse exchange — repack expert-major and all_to_all back,
    # landing in the original [E, C, D] layout on each source device.
    packed = (processed.reshape(e_local, n_dev, capacity, D)
              .transpose(1, 0, 2, 3).reshape(E, capacity, D))
    returned = lax.all_to_all(packed, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)

    # k routes per token: gather each route's processed row, weight, sum.
    out_routes = returned[expert_of, jnp.where(valid, slot_of, 0)]  # [T,k,D]
    out_routes = jnp.where(valid[..., None], out_routes, 0.0)
    out = (out_routes * gate[..., None]).sum(axis=1)
    if return_aux:
        return out, load_balance_loss(gate_logits, expert_of, E,
                                      probs=probs)
    return out

"""Parallelism strategies: hierarchical collectives, gradient sync, parameter
server.  See SURVEY.md §3.3 for the strategy inventory this mirrors."""

from . import hierarchical  # noqa: F401  (registers the "hierarchical" backend)

"""Parallelism strategies on the shared communicator tree.

- data parallel (sync): :mod:`gradsync` (+ the ``nn``/``recipes`` facades)
- data parallel (async): :mod:`ps` (Downpour/EASGD parameter server)
- hierarchical 2-level collectives: :mod:`hierarchical`
- tensor parallel: :mod:`tensor` | pipeline: :mod:`pipeline`
- sequence/context parallel: :mod:`sequence` | expert: :mod:`expert`

See SURVEY.md §3.3 for which of these existed in the reference (DP only)
and docs/PARITY.md for the full map.
"""

from . import hierarchical  # noqa: F401  (registers the "hierarchical" backend)
from . import gradsync  # noqa: F401
from . import zero  # noqa: F401
from . import ps  # noqa: F401
from . import sequence  # noqa: F401
from . import tensor  # noqa: F401
from . import pipeline  # noqa: F401
from . import expert  # noqa: F401

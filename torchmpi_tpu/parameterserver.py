"""``torchmpi_tpu.parameterserver`` — the ``torchmpi.parameterserver``
integration surface (SURVEY.md §3 C11, reconstructed — reference mount
empty).  Thin facade over :mod:`torchmpi_tpu.parallel.ps` keeping the
reference's module layout and verbs (init/send/receive/syncHandle)."""

from .parallel.ps import (  # noqa: F401
    RULES,
    PSHandle,
    PSClient,
    ShardedParameterServer,
    ParameterServer,
    sync_handle,
)


def init(template, num_shards: int = 2, **kw) -> ParameterServer:
    """Reference: ``parameterserver.init(flatParams)`` — starts shard servers
    and connects a client, seeding shards with ``template``'s values."""
    return ParameterServer(template, num_shards=num_shards, **kw)

"""``torchmpi_tpu.nn`` — the ``torchmpi.nn`` integration surface.

Thin facade over :mod:`torchmpi_tpu.parallel.gradsync` keeping the reference's
module layout (``torchmpi/nn.lua``, SURVEY.md §3 C10): users who knew
``mpinn.synchronizeParameters`` / ``mpinn.synchronizeGradients`` find the same
verbs here; the TPU-native step builder lives alongside.
"""

from .parallel.gradsync import (  # noqa: F401
    synchronize_parameters,
    resynchronize_parameters_in_axis,
    synchronize_gradients,
    accumulate_gradients,
    data_parallel_step,
)

__all__ = [
    "synchronize_parameters",
    "resynchronize_parameters_in_axis",
    "synchronize_gradients",
    "accumulate_gradients",
    "data_parallel_step",
]

"""``torchmpi_tpu.nn`` — the ``torchmpi.nn`` integration surface.

Thin facade over :mod:`torchmpi_tpu.parallel.gradsync` keeping the reference's
module layout (``torchmpi/nn.lua``, SURVEY.md §3 C10): users who knew
``mpinn.synchronizeParameters`` / ``mpinn.synchronizeGradients`` find the same
verbs here; the TPU-native step builder lives alongside.

``synchronize_gradients`` rides the fused pytree collectives
(:mod:`torchmpi_tpu.fusion`, ``config.fuse_max_bytes``): a parameter
tree's gradients coalesce into dtype-grouped, size-bounded flat buckets
— O(dtypes x buckets) collective launches per step instead of one per
layer, the coalescing the reference's async per-layer hooks fed into
its chunked collectives.
"""

from .parallel.gradsync import (  # noqa: F401
    synchronize_parameters,
    resynchronize_parameters_in_axis,
    synchronize_gradients,
    make_overlapped_grad_fn,
    accumulate_gradients,
    data_parallel_step,
)

__all__ = [
    "synchronize_parameters",
    "resynchronize_parameters_in_axis",
    "synchronize_gradients",
    "make_overlapped_grad_fn",
    "accumulate_gradients",
    "data_parallel_step",
]

"""TorchMPI-naming compatibility surface.

A user of the reference (``require('torchmpi')``, SURVEY.md §3 C9 —
reconstructed, reference mount empty) finds the same verbs here under the
names they knew.  These are thin aliases — the library's native snake_case
API is the primary surface; this module documents the 1:1 mapping and keeps
migration mechanical:

    import torchmpi_tpu.compat as mpi
    mpi.start()                       # mpi.start(withCuda)
    mpi.allreduceTensor(t)            # in place of torchmpi's tensor verb
    h = mpi.async_.allreduceTensor(t)
    mpi.syncHandle(h)
    mpinn = torchmpi_tpu.compat.nn    # torchmpi.nn
    mpinn.synchronizeParameters(net_params)
    mpinn.synchronizeGradients(grads)
    mpi.stop()

Knob setters mirror the reference's C-level FFI setters
(``torchmpi_set_flat_collectives`` etc., SURVEY.md §6.6).
"""

from __future__ import annotations

from types import SimpleNamespace

from . import collectives as _collectives
from . import runtime as _runtime
from .parallel import gradsync as _gradsync

# --- runtime ---------------------------------------------------------------


def start(use_accelerator: bool = True, **kw):
    """Reference: ``mpi.start(withCuda)``."""
    return _runtime.init(use_accelerator=use_accelerator, **kw)


stop = _runtime.stop
rank = _runtime.rank
size = _runtime.size
barrier = _runtime.barrier
localRank = _runtime.local_rank

# --- knob setters (reference: torchmpi_set_* FFI functions) ---------------


_pre_hierarchical_backend: list = []


def set_flat_collectives():
    """Restore the backend that was active before
    ``set_hierarchical_collectives`` (default ``xla``) — just clearing the
    flag would leave backend='hierarchical' silently routing the same way."""
    prev = _pre_hierarchical_backend.pop() if _pre_hierarchical_backend \
        else "xla"
    _runtime.set_config(hierarchical=False, backend=prev)


def set_hierarchical_collectives():
    _pre_hierarchical_backend.append(_runtime.config().backend)
    _runtime.set_config(hierarchical=True, backend="hierarchical")


def set_staged_collectives():
    """Reference: ``torchmpi_set_staged_collectives`` — GPU tensors were
    staged through pinned host buffers when MPI was not CUDA-aware
    (SURVEY.md §6.6, §3 C5).  TPU mapping: the eager tensor verbs
    round-trip device -> host -> device with the reduction on the host
    CPU (``config.staged``); in-axis collectives inside jit are always
    direct — XLA/ICI is "CUDA-aware" by construction — so, as in the
    reference, staged is the debugging/bring-up fallback and direct the
    performant default.  See docs/MIGRATION.md."""
    _runtime.set_config(staged=True)


def set_direct_collectives():
    """Reference: ``torchmpi_set_direct_collectives`` (the default)."""
    _runtime.set_config(staged=False)


def set_chunk_size(nbytes: int):
    _runtime.set_config(chunk_bytes=int(nbytes))


def set_min_bytes_for_custom(nbytes: int):
    _runtime.set_config(custom_min_bytes=int(nbytes))


def collectiveSelector(backend: str):
    """Reference: assigning into ``mpi.collectiveSelector``."""
    _runtime.set_config(backend=backend)


def collectiveAvailability():
    """Reference: ``mpi.collectiveAvailability`` introspection."""
    from . import selector

    return selector.available()


# --- tensor collectives ----------------------------------------------------

allreduceTensor = _collectives.allreduce
broadcastTensor = _collectives.broadcast
reduceTensor = _collectives.reduce
allgatherTensor = _collectives.allgather
gatherTensor = _collectives.gather
scatterTensor = _collectives.scatter
sendreceiveTensor = _collectives.sendreceive
reduce_scatterTensor = _collectives.reduce_scatter
alltoallTensor = _collectives.alltoall
syncHandle = _collectives.sync_handle

# The async namespace mirrors the sync verb set 1:1 (VERDICT r4
# missing #2: the compat surface claims the full mapping, so every op
# the native ``collectives.async_`` has must appear here too).
async_ = SimpleNamespace(
    allreduceTensor=_collectives.async_.allreduce,
    broadcastTensor=_collectives.async_.broadcast,
    reduceTensor=_collectives.async_.reduce,
    allgatherTensor=_collectives.async_.allgather,
    gatherTensor=_collectives.async_.gather,
    scatterTensor=_collectives.async_.scatter,
    sendreceiveTensor=_collectives.async_.sendreceive,
    reduce_scatterTensor=_collectives.async_.reduce_scatter,
    alltoallTensor=_collectives.async_.alltoall,
)

# --- integration layers ----------------------------------------------------

nn = SimpleNamespace(
    synchronizeParameters=_gradsync.synchronize_parameters,
    synchronizeGradients=_gradsync.synchronize_gradients,
)


def parameterserver():
    from . import parameterserver as ps

    return ps

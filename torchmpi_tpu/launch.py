"""Process launcher — the reference's ``scripts/`` + mpirun role (SURVEY.md
§3 C17, reconstructed — reference mount empty).

On a real TPU pod there is nothing to launch: one process per host starts
via the platform's own tooling and ``init()`` reads the slice metadata.
What remains useful — and what the reference's mpirun wrappers actually
provided — is *local multi-process bring-up for development and tests*:

    python -m torchmpi_tpu.launch --nproc 2 --devices-per-proc 2 script.py ...

spawns N processes on this host wired together through ``jax.distributed``
over a localhost coordinator (CPU devices, gloo collectives), each with
``TORCHMPI_TPU_PROCESS_ID`` / ``_NUM_PROCESSES`` / ``_COORDINATOR`` set; the
launched script just calls ``torchmpi_tpu.init()``.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m torchmpi_tpu.launch", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--nproc", type=int, default=2,
                   help="number of processes (emulated hosts)")
    p.add_argument("--devices-per-proc", type=int, default=2,
                   help="simulated CPU devices per process")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    port = _free_port()
    procs = []
    for pid in range(args.nproc):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count="
              f"{args.devices_per_proc}").strip()
        env["TORCHMPI_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
        env["TORCHMPI_TPU_NUM_PROCESSES"] = str(args.nproc)
        env["TORCHMPI_TPU_PROCESS_ID"] = str(pid)
        # All launched processes share this host, so the local rank IS the
        # process id (consumed by runtime.local_rank()).
        env["TORCHMPI_TPU_LOCAL_RANK"] = str(pid)
        env["TORCHMPI_TPU_LOCAL_CPU"] = "1"
        procs.append(subprocess.Popen(
            [sys.executable, args.script] + args.script_args, env=env))
    # mpirun semantics: first nonzero exit kills the remaining ranks (a
    # surviving rank would otherwise block forever in a collective whose
    # peer died).
    import time

    rc = 0
    live = list(procs)
    while live:
        for p_ in list(live):
            code = p_.poll()
            if code is None:
                continue
            live.remove(p_)
            if code != 0 and rc == 0:
                rc = code
                for other in live:
                    other.terminate()
        time.sleep(0.05)
    if rc:
        for p_ in procs:
            if p_.poll() is None:
                p_.kill()
    return rc


if __name__ == "__main__":
    raise SystemExit(main())

"""Fused pytree collectives: dtype-grouped leaf coalescing.

TorchMPI's core perf trick was coalescing/chunking tensor traffic
(PAPER.md §4.2/§4.3: custom chunked-pipelined collectives, per-layer
async hooks feeding a coalescing engine); the in-axis API used to do the
opposite — ``jax.tree.map`` one collective launch per leaf, so a
transformer parameter tree issued hundreds of tiny collectives whose
per-leaf sizes also defeated the selector cutover and the tuning plans
(each leaf keyed at its tiny size, never the real transfer).

This module is the coalescing layer, the same shape as PyTorch DDP's
gradient-bucket fusion:

- Leaves are grouped **by dtype, never promoted** — a mixed fp32/bf16
  tree keeps bf16 leaves bf16 on the wire (the old ``FlatSpec``
  ``result_type`` concat upcast them all to fp32, doubling their bytes).
- Each group concatenates into a flat buffer split into size-bounded
  **buckets** (``config.fuse_max_bytes``; 0 disables fusion), and ONE
  selector-routed collective is issued per bucket — ``selector.select``
  and the tuning plans see the true fused nbytes, O(dtypes x buckets)
  launches instead of O(leaves).
- The result unflattens back to the original tree (original shapes;
  dtypes come out of the wire untouched because no promotion happened).

:class:`FusedSpec` is also the shared flatten metadata for the bucketed
gradient allreduce (``parallel/gradsync``) and the ZeRO shard layout
(``parallel/zero``) — it subsumes the old ``gradsync.FlatSpec``
(single-dtype trees produce byte-identical layouts; mixed-dtype trees
now lay out group-major with per-group padding so the per-dtype wire
legs and the promoted optimizer view can never disagree about which
extent a device owns).

Numerics: fusion never changes results.  The fused reductions are
elementwise over a repacked buffer, so every element sees the same
cross-device reduction order as the per-leaf launch — fused == per-leaf
bit-for-bit, per dtype (``tests/test_fusion.py`` asserts exact
equality, and that the lowered HLO collective count actually drops).
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import runtime

PyTree = Any

# ---------------------------------------------------------------------------
# Trace-time analysis hook (torchmpi_tpu.analysis, rule C1).  The
# analyzer installs a listener around its make_jaxpr trace; fused
# launches and the ZeRO reduce-scatter legs then describe their layout
# (spec-vs-tree agreement, barrier chain coverage, shard alignment) as
# plain dict records.  One None-check per *trace* when no listener is
# installed — zero per-step runtime cost.
# ---------------------------------------------------------------------------

_trace_listener: Optional[Any] = None


def set_trace_listener(fn):
    """Install (``fn``) or clear (``None``) the analysis record
    listener; returns the previous listener so nested checks restore
    it."""
    global _trace_listener
    prev = _trace_listener
    _trace_listener = fn
    return prev


def _emit_trace_record(record: dict) -> None:
    if _trace_listener is not None:
        _trace_listener(record)


def _record_source() -> str:
    """Best-effort user call-site (``file.py:line``) for a record —
    the first stack frame outside this package."""
    import traceback

    pkg = os.path.dirname(os.path.abspath(__file__))
    for fr in reversed(traceback.extract_stack()[:-2]):
        if not os.path.abspath(fr.filename).startswith(pkg):
            return f"{fr.filename}:{fr.lineno}"
    return ""


# In-axis ops with elementwise, shape-preserving semantics: reducing (or
# copying) a concatenated buffer is exactly the concatenation of the
# per-leaf results, so coalescing is transparent.  reduce_scatter has
# its own tile-interleaved path (:func:`maybe_fuse_reduce_scatter`);
# gather/allgather/scatter/alltoall change shapes per-leaf and stay on
# the tree.map path.
ELEMENTWISE_OPS = ("allreduce", "reduce", "broadcast")


class _DtypeGroup:
    """One dtype's slice of a :class:`FusedSpec`: which leaves, their
    layout in the group-flat buffer, padding, and bucket bounds."""

    __slots__ = ("dtype", "indices", "shapes", "sizes", "total", "padded",
                 "shard", "bounds", "leaf_buckets")

    def __init__(self, dtype):
        self.dtype = dtype
        self.indices: List[int] = []   # positions in the flattened tree
        self.shapes: List[Tuple[int, ...]] = []
        self.sizes: List[int] = []
        self.total = 0

    @property
    def nbytes(self) -> int:
        return self.total * np.dtype(self.dtype).itemsize


def _proportional_buckets(groups: Sequence[_DtypeGroup], k: int) -> List[int]:
    """Distribute ~``k`` buckets across groups proportionally to their
    byte share, at least one each (single-group trees get exactly ``k``,
    preserving the pre-fusion ``gradsync_buckets`` contract)."""
    tot = sum(g.nbytes for g in groups) or 1
    return [max(1, min(max(1, g.total), round(k * g.nbytes / tot)))
            for g in groups]


class FusedSpec:
    """Static fusion metadata for one pytree.

    Layout is **group-major**: leaves grouped by dtype (first-seen
    order), each group concatenated flat in leaf order and padded to a
    multiple of ``n_shards``.  Bucketing within a group is either
    byte-bounded (``max_bytes``, the in-axis fusion knob) or
    count-driven (``n_buckets``, the ``gradsync_buckets`` contract).

    Also carries the promoted single-buffer view the ZeRO optimizer
    math runs in (``dtype``/``padded``/``shard`` — the wire stays
    per-dtype; only the local shard promotes): the drop-in replacement
    for the old ``gradsync.FlatSpec``.
    """

    def __init__(self, tree: PyTree, n_shards: int = 1, *,
                 max_bytes: Optional[int] = None,
                 n_buckets: Optional[int] = None):
        leaves, self.treedef = jax.tree.flatten(tree)
        self.n_leaves = len(leaves)
        self.n_shards = int(n_shards)
        self.shapes = [l.shape for l in leaves]
        self.dtypes = [np.dtype(l.dtype) for l in leaves]
        self.sizes = [int(np.prod(s)) for s in self.shapes]
        self.total = int(sum(self.sizes))
        self.dtype = jnp.result_type(*self.dtypes) if leaves else jnp.float32

        by_dtype = {}
        self.groups: List[_DtypeGroup] = []
        for i, (shape, dt, size) in enumerate(
                zip(self.shapes, self.dtypes, self.sizes)):
            g = by_dtype.get(dt)
            if g is None:
                g = by_dtype[dt] = _DtypeGroup(dt)
                self.groups.append(g)
            g.indices.append(i)
            g.shapes.append(shape)
            g.sizes.append(size)
            g.total += size
        for g in self.groups:
            g.padded = max(self.n_shards,
                           -(-g.total // self.n_shards) * self.n_shards)
            g.shard = g.padded // self.n_shards

        # Promoted view: per-group padding, group-major concat.
        self.padded = (sum(g.padded for g in self.groups)
                       or self.n_shards)
        self.shard = self.padded // self.n_shards

        # Element-granularity bucket bounds per group (for the
        # elementwise ops) ...
        if n_buckets is not None:
            ks = _proportional_buckets(self.groups,
                                       max(1, int(n_buckets)))
        elif max_bytes and max_bytes > 0:
            ks = [max(1, min(max(1, g.total),
                             -(-g.nbytes // int(max_bytes))))
                  for g in self.groups]
        else:
            ks = [1] * len(self.groups)
        for g, k in zip(self.groups, ks):
            edges = np.linspace(0, g.total, k + 1).astype(int)
            g.bounds = [(int(edges[i]), int(edges[i + 1]))
                        for i in range(k) if edges[i] < edges[i + 1]]
            if not g.bounds:  # all-empty group: one degenerate bucket
                g.bounds = [(0, g.total)]
        # ... and leaf-granularity buckets (for reduce_scatter, where a
        # bucket boundary inside a leaf would break tile alignment):
        # greedy first-fit in leaf order against the same byte bound.
        limit = int(max_bytes) if (max_bytes and max_bytes > 0) else 0
        for g in self.groups:
            itemsize = np.dtype(g.dtype).itemsize
            buckets, acc = [[]], 0
            for pos, size in enumerate(g.sizes):
                b = size * itemsize
                if buckets[-1] and limit and acc + b > limit:
                    buckets.append([])
                    acc = 0
                buckets[-1].append(pos)
                acc += b
            g.leaf_buckets = buckets

    @property
    def n_launches(self) -> int:
        """Collectives one fused elementwise op issues for this tree."""
        return sum(len(g.bounds) for g in self.groups)


def group_flat(leaves: Sequence, g: _DtypeGroup, *, pad: bool = False):
    """Concatenate ``g``'s leaves (native dtype, no promotion) into one
    flat buffer, optionally zero-padded to ``g.padded``."""
    parts = [leaves[i].reshape(-1) for i in g.indices]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    if pad and g.padded > g.total:
        flat = jnp.pad(flat, (0, g.padded - g.total))
    return flat


def _unpack_group(flat, g: _DtypeGroup, out_leaves: List) -> None:
    """Slice ``g``'s leaves back out of its (reduced) flat buffer.  No
    dtype cast: the wire never promoted, so ``flat`` already has the
    right dtype (or the reducer's own promotion — int pmean -> f32 —
    which per-leaf launches produce identically)."""
    off = 0
    for i, shape, size in zip(g.indices, g.shapes, g.sizes):
        out_leaves[i] = flat[off:off + size].reshape(shape)
        off += size


# ---------------------------------------------------------------------------
# Fused elementwise collectives (allreduce / reduce / broadcast)
# ---------------------------------------------------------------------------


def fuse_tree(op_name: str, tree: PyTree, axes: Tuple[str, ...], *,
              backend: Optional[str] = None, barrier: bool = False,
              spec: Optional[FusedSpec] = None,
              impls: Optional[Sequence] = None, **params) -> PyTree:
    """One selector-routed collective per (dtype group x bucket).

    ``barrier=True`` chains each bucket's input on the previous bucket's
    output through ``lax.optimization_barrier`` — the
    ``gradsync_barrier`` overlap lever, unchanged, now applied to the
    group-native buffers instead of one promoted concat.  The chain
    crosses dtype-group boundaries (a group's first bucket depends on
    the previous group's last), so ALL buckets stay distinct through
    XLA's all-reduce combiner, exactly as the old single-concat chain
    kept them.

    ``impls`` is the planner's replay mode (torchmpi_tpu/planner.py):
    one pre-picked implementation per bucket, in this function's
    iteration order (group-major, then bucket order) — the per-bucket
    ``_pick`` is then skipped entirely.
    """
    from .collectives import _pick  # lazy: collectives imports us

    leaves = jax.tree.leaves(tree)
    if spec is None:
        spec = FusedSpec(tree)
    out_leaves: List = [None] * spec.n_leaves
    prev = None
    links = 0
    launch = 0
    for g in spec.groups:
        flat = group_flat(leaves, g)
        parts = []
        for lo, hi in g.bounds:
            part = flat[lo:hi]
            if barrier and prev is not None:
                part, _ = lax.optimization_barrier((part, prev))
                links += 1
            impl = (impls[launch] if impls is not None
                    else _pick(op_name, part, backend, axes))
            launch += 1
            prev = impl(part, axes, **params)
            parts.append(prev)
        gout = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        _unpack_group(gout, g, out_leaves)
    if runtime.effective_config().obs != "off":
        from . import obs

        # Trace-time accounting: leaves coalesced, launches issued, and
        # wire bytes vs the promoted-concat layout fusion replaced.
        wire = sum(g.nbytes for g in spec.groups)
        promoted = spec.total * np.dtype(spec.dtype).itemsize
        obs.record_fusion(op_name, spec.n_leaves, spec.n_launches, wire,
                          max(0, promoted - wire))
    if _trace_listener is not None:
        _emit_trace_record(dict(
            kind="fuse_tree", op=op_name, axes=tuple(axes),
            source=_record_source(),
            spec_leaves=spec.n_leaves, tree_leaves=len(leaves),
            spec_dtypes=[np.dtype(d).name for d in spec.dtypes],
            tree_dtypes=[np.dtype(l.dtype).name for l in leaves
                         if hasattr(l, "dtype")],
            spec_sizes=list(spec.sizes),
            tree_sizes=[int(np.prod(l.shape)) for l in leaves
                        if hasattr(l, "shape")],
            n_launches=spec.n_launches, barrier=bool(barrier),
            barrier_links=links))
    return jax.tree.unflatten(spec.treedef, out_leaves)


def _fusable_leaves(leaves: Sequence) -> bool:
    return all(hasattr(l, "shape") and hasattr(l, "dtype") for l in leaves)


def maybe_fuse(op_name: str, tree: PyTree, axes: Tuple[str, ...], *,
               backend: Optional[str] = None, **params) -> Optional[PyTree]:
    """Fuse an in-axis pytree collective, or return ``None`` for the
    per-leaf path: fusion disabled (``config.fuse_max_bytes == 0``),
    fewer than two array leaves, non-array leaves (python scalars), or
    a bucketing that would not reduce the launch count anyway."""
    max_bytes = runtime.effective_config().fuse_max_bytes
    if max_bytes <= 0 or op_name not in ELEMENTWISE_OPS:
        return None
    leaves = jax.tree.leaves(tree)
    if len(leaves) < 2 or not _fusable_leaves(leaves):
        return None
    spec = FusedSpec(tree, max_bytes=max_bytes)
    if spec.n_launches >= spec.n_leaves:
        return None  # pure overhead: as many launches as tree.map
    return fuse_tree(op_name, tree, axes, backend=backend, spec=spec,
                     **params)


# ---------------------------------------------------------------------------
# Fused reduce_scatter: tile-interleaved layout
# ---------------------------------------------------------------------------


def maybe_fuse_reduce_scatter(tree: PyTree, axes: Tuple[str, ...], *,
                              backend: Optional[str] = None,
                              op: str = "sum") -> Optional[PyTree]:
    """Fused per-leaf-preserving reduce_scatter, or ``None`` for the
    per-leaf path.

    A scatter of a plain concat would hand device ``i`` one contiguous
    extent of the fused buffer — not each leaf's tile ``i``.  Instead
    each leaf is viewed as its ``n`` tiles (``leaf.reshape(n, -1)``)
    and the bucket concatenates ALONG the tile axis, so the scattered
    extent ``i`` is exactly ``[leaf0_tile_i | leaf1_tile_i | ...]`` —
    bit-for-bit the per-leaf result, one collective per bucket.
    Requires every leaf's leading dim divisible by the group size (the
    same precondition the per-leaf tiled scatter imposes); trees that
    do not satisfy it fall back per-leaf.
    """
    max_bytes = runtime.effective_config().fuse_max_bytes
    if max_bytes <= 0:
        return None
    leaves = jax.tree.leaves(tree)
    if len(leaves) < 2 or not _fusable_leaves(leaves):
        return None
    try:
        n = 1
        for a in axes:
            n *= lax.axis_size(a)
    except Exception:  # noqa: BLE001 — outside an axis binding: per-leaf
        return None
    if n <= 0 or any(l.ndim < 1 or l.shape[0] % n != 0 for l in leaves):
        return None
    spec = FusedSpec(tree, max_bytes=max_bytes)
    n_launches = sum(len(g.leaf_buckets) for g in spec.groups)
    if n_launches >= spec.n_leaves:
        return None
    return fused_reduce_scatter(tree, axes, spec=spec, n=n,
                                backend=backend, op=op)


def fused_reduce_scatter(tree: PyTree, axes: Tuple[str, ...], *,
                         spec: FusedSpec, n: int,
                         backend: Optional[str] = None,
                         impls: Optional[Sequence] = None,
                         op: str = "sum") -> PyTree:
    """Execute the fused tile-interleaved reduce_scatter for a tree
    whose layout decision (``spec``, and optionally the per-bucket
    ``impls`` in group-major leaf-bucket order — the planner's replay
    mode) was already taken; ``n`` is the spanned axis-size product the
    tiling divides by."""
    from .collectives import _pick  # lazy: collectives imports us

    leaves = jax.tree.leaves(tree)
    out_leaves: List = [None] * spec.n_leaves
    launch = 0
    for g in spec.groups:
        for bucket in g.leaf_buckets:
            tiles = [leaves[g.indices[pos]].reshape(n, -1)
                     for pos in bucket]
            flat = (tiles[0] if len(tiles) == 1
                    else jnp.concatenate(tiles, axis=1)).reshape(-1)
            impl = (impls[launch] if impls is not None
                    else _pick("reduce_scatter", flat, backend, axes))
            launch += 1
            shard = impl(flat, axes, op=op)
            off = 0
            for pos in bucket:
                i, shape = g.indices[pos], g.shapes[pos]
                ts = g.sizes[pos] // n
                out_leaves[i] = shard[off:off + ts].reshape(
                    (shape[0] // n,) + tuple(shape[1:]))
                off += ts
    return jax.tree.unflatten(spec.treedef, out_leaves)


# ---------------------------------------------------------------------------
# ZeRO shard layout (the old gradsync.FlatSpec contract, group-major)
# ---------------------------------------------------------------------------


def flatten_tree(tree: PyTree, spec: FusedSpec) -> jax.Array:
    """Concat all leaves into one flat vector, promoted to
    ``spec.dtype``: group-major layout, each group zero-padded to a
    multiple of ``spec.n_shards``.  Single-dtype trees reproduce the
    old ``gradsync.flatten_tree`` layout exactly."""
    leaves = jax.tree.leaves(tree)
    parts = [group_flat(leaves, g, pad=True).astype(spec.dtype)
             for g in spec.groups]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def unflatten_tree(flat: jax.Array, spec: FusedSpec) -> PyTree:
    """Inverse of :func:`flatten_tree`: slice, reshape, and cast each
    leaf back to its original dtype (padding dropped)."""
    out_leaves: List = [None] * spec.n_leaves
    off = 0
    for g in spec.groups:
        gf = flat[off:off + g.padded]
        off += g.padded
        goff = 0
        for i, shape, size in zip(g.indices, g.shapes, g.sizes):
            out_leaves[i] = gf[goff:goff + size].reshape(shape).astype(
                spec.dtypes[i])
            goff += size
    return jax.tree.unflatten(spec.treedef, out_leaves)


def local_shard(tree: PyTree, spec: FusedSpec, index) -> jax.Array:
    """Device ``index``'s flat promoted shard: each dtype group's extent
    ``index``, concatenated in group order — THE ZeRO shard
    linearization, chosen so it equals what the per-group (native
    dtype) fused reduce_scatter hands each device, promoted."""
    leaves = jax.tree.leaves(tree)
    parts = []
    for g in spec.groups:
        flat = group_flat(leaves, g, pad=True).astype(spec.dtype)
        parts.append(lax.dynamic_slice(flat, (index * g.shard,),
                                       (g.shard,)))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def unflatten_shards(flat: jax.Array, spec: FusedSpec) -> PyTree:
    """Rebuild the tree from the all-gather of per-device
    :func:`local_shard` outputs (``flat`` is their rank-order concat,
    ``spec.n_shards * spec.shard`` elements): regroup each group's
    per-device extents back into its padded flat, then unflatten."""
    rows = flat.reshape(spec.n_shards, spec.shard)
    out_leaves: List = [None] * spec.n_leaves
    col = 0
    for g in spec.groups:
        gf = rows[:, col:col + g.shard].reshape(-1)
        col += g.shard
        goff = 0
        for i, shape, size in zip(g.indices, g.shapes, g.sizes):
            out_leaves[i] = gf[goff:goff + size].reshape(shape).astype(
                spec.dtypes[i])
            goff += size
    return jax.tree.unflatten(spec.treedef, out_leaves)

"""Fused linear + softmax cross-entropy Pallas kernel.

The LM-head loss is the other memory hog of long-context training (after
attention): computing ``softmax_xent(x @ W, labels)`` materializes a
[tokens, vocab] logits matrix (plus its f32 softmax) in HBM.  This kernel
streams vocab blocks through VMEM with an online log-sum-exp — logits never
exist in memory — and the custom VJP recomputes probabilities blockwise for
``dx`` and ``dW``, so peak memory is O(block) instead of O(tokens x vocab).

No reference analog (TorchMPI predates transformers; SURVEY.md §6.7) —
this serves the beyond-reference long-context stack next to ops/flash.py,
with the same grid-scratch accumulation idiom: the (m, l, t) running state
carries across the minor vocab-block grid dimension.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import ring

from .flash import NEG_INF, _float0_zero

_LANES = 128
_STAT_LANES = 8



def _xent_fwd_kernel(labels_ref, x_ref, w_ref, loss_ref, lse_ref, m_scr,
                     l_scr, t_scr, *, block_n: int, block_v: int,
                     vocab: int, pad_vocab: bool):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        t_scr[:] = jnp.zeros_like(t_scr)

    z = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # [block_n, block_v]
    col = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)
    if pad_vocab:
        # Statically skipped when vocab % block_v == 0 (the production
        # case): no padded w columns exist, so the select is the
        # identity — one fewer [block_n, block_v] VPU pass per block.
        # The iota stays either way (the label-hit compare needs col).
        z = jnp.where(col < vocab, z, NEG_INF)  # mask vocab padding

    m_prev = jnp.max(m_scr[:], axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, jnp.max(z, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    l_prev = jnp.max(l_scr[:], axis=1, keepdims=True)
    l_new = alpha * l_prev + jnp.sum(jnp.exp(z - m_new), axis=1,
                                     keepdims=True)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    # The label's logit, accumulated when its column passes through.
    lab = labels_ref[:]  # [block_n, 1] int32
    hit = jnp.where(col == lab, z, 0.0)
    t_scr[:] = t_scr[:] + jnp.broadcast_to(
        jnp.sum(hit, axis=1, keepdims=True), t_scr.shape)

    @pl.when(j == nv - 1)
    def _finalize():
        lse = m_new + jnp.log(jnp.maximum(l_new, 1e-37))
        t = jnp.max(t_scr[:], axis=1, keepdims=True)
        loss_ref[:] = jnp.broadcast_to(lse - t, loss_ref.shape)
        lse_ref[:] = jnp.broadcast_to(lse, lse_ref.shape)


def _xent_bwd_dx_kernel(labels_ref, x_ref, w_ref, lse_ref, dl_ref, dx_ref,
                        dx_acc, *, block_n: int, block_v: int,
                        vocab: int, pad_vocab: bool):
    """dx_i = dloss_i * sum_v (p_iv - y_iv) W_v^T, p recomputed from lse."""
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        dx_acc[:] = jnp.zeros_like(dx_acc)

    z = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    col = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)
    if pad_vocab:  # see _xent_fwd_kernel: identity when unpadded
        z = jnp.where(col < vocab, z, NEG_INF)
    lse = jnp.max(lse_ref[:], axis=1, keepdims=True)
    p = jnp.exp(z - lse)  # vocab-padding cols give 0
    y = (col == labels_ref[:]).astype(jnp.float32)
    dl = jnp.max(dl_ref[:], axis=1, keepdims=True)
    g = (p - y) * dl  # [block_n, block_v]
    dx_acc[:] = dx_acc[:] + jax.lax.dot_general(
        g.astype(w_ref.dtype), w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == nv - 1)
    def _finalize():
        dx_ref[:] = dx_acc[:].astype(dx_ref.dtype)


def _xent_bwd_dw_kernel(labels_ref, x_ref, w_ref, lse_ref, dl_ref, dw_ref,
                        dw_acc, *, block_n: int, block_v: int,
                        vocab: int, pad_vocab: bool):
    """dW_v = sum_i x_i^T (p_iv - y_iv) dloss_i.  Grid (nv, nn): the token
    dimension is minor so the dW accumulator carries across it."""
    i = pl.program_id(1)
    nn = pl.num_programs(1)

    @pl.when(i == 0)
    def _init():
        dw_acc[:] = jnp.zeros_like(dw_acc)

    j = pl.program_id(0)
    z = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    col = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)
    if pad_vocab:  # see _xent_fwd_kernel: identity when unpadded
        z = jnp.where(col < vocab, z, NEG_INF)
    lse = jnp.max(lse_ref[:], axis=1, keepdims=True)
    p = jnp.exp(z - lse)
    y = (col == labels_ref[:]).astype(jnp.float32)
    dl = jnp.max(dl_ref[:], axis=1, keepdims=True)
    g = (p - y) * dl
    dw_acc[:] = dw_acc[:] + jax.lax.dot_general(
        x_ref[:], g.astype(x_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(i == nn - 1)
    def _finalize():
        dw_ref[:] = dw_acc[:].astype(dw_ref.dtype)


def _pad_rows(a, block, fill=0):
    pad = (-a.shape[0]) % block
    if pad:
        a = jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1),
                    constant_values=fill)
    return a


def _stats(x, n_pad):
    """[N] -> [N_pad, _STAT_LANES] broadcast blocks."""
    x = jnp.pad(x, ((0, n_pad - x.shape[0]),))
    return jnp.broadcast_to(x[:, None], (x.shape[0], _STAT_LANES))


def _interp():
    return ring._interpret_mode()


# Mosaic's default scoped-VMEM budget is 16 MiB — tuned for small kernels,
# not for an LM-head block carrying two [E, block_v] f32 accumulators plus
# double-buffered bf16 operand blocks (at E=2048, block_v=512 the dW pass
# needs ~17 MiB and the first real-silicon stage-B' run died on exactly
# that).  v5e/v5p have 128 MiB of physical VMEM; declare an honest larger
# scope and, for truly huge shapes, shrink the vocab block until the
# estimate fits.
_VMEM_LIMIT = 100 * 1024 * 1024
_VMEM_BUDGET = 88 * 1024 * 1024


def _bwd_vmem_bytes(bn: int, bv: int, embed: int, ds: int) -> int:
    """Upper-bound scoped-VMEM estimate for the backward pass: the max
    of the dx and dW kernels' footprints (each: double-buffered input
    blocks, double-buffered f32 output + f32 accumulator scratch, and
    ~4 [bn, bv] f32 temporaries for z/p/g/col).  dW's out/accumulator
    scale with E*bv, dx's with bn*E — both must fit (code review r4:
    modelling only dW passes configs whose dx kernel overflows)."""
    ins = 2 * (bn * embed + embed * bv) * ds
    temps = 4 * bn * bv * 4
    dw = ins + 3 * embed * bv * 4 + temps
    dx = ins + 3 * bn * embed * 4 + temps
    return max(dw, dx)


def _fit_blocks(bn: int, bv: int, embed: int, ds: int):
    """Shrink (block_n, block_v) until the backward estimate fits the
    scoped-VMEM budget.  Vocab blocks shrink first (the [E, bv] f32
    accumulators dominate); 128 is the lane-tile floor for both."""
    while _bwd_vmem_bytes(bn, bv, embed, ds) > _VMEM_BUDGET and bv > _LANES:
        bv = max(_LANES, bv // 2)
    while _bwd_vmem_bytes(bn, bv, embed, ds) > _VMEM_BUDGET and bn > _LANES:
        bn = max(_LANES, bn // 2)
    return bn, bv


def _kernel_params(interpret):
    """Compiler params for the device-local xent kernels: the interpret
    barrier skip (ring.local_kernel_params) under interpret; on real
    TPU lowering the raised scoped-VMEM limit plus grid semantics — all
    three kernels run 2-D grids whose scratch carries only across the
    MINOR dim (re-initialized at its first step), so the major dim is
    parallel and Mosaic may pipeline across it (see
    flash._flash_params)."""
    if interpret:
        return ring.local_kernel_params(interpret)
    return pltpu.CompilerParams(
        vmem_limit_bytes=_VMEM_LIMIT,
        dimension_semantics=("parallel", "arbitrary"))


def _fused_xent_fwd(x, w, labels, block_n: int, block_v: int, interpret):
    N, E = x.shape
    V = w.shape[1]
    block_n = min(block_n, N)
    block_v = min(block_v, V)
    xp = _pad_rows(x, block_n)
    labp = _pad_rows(labels.astype(jnp.int32)[:, None], block_n, fill=-1)
    pad_v = (-V) % block_v
    wp = jnp.pad(w, ((0, 0), (0, pad_v))) if pad_v else w
    Np, Vp = xp.shape[0], wp.shape[1]
    grid = (Np // block_n, Vp // block_v)
    kern = functools.partial(_xent_fwd_kernel, block_n=block_n,
                             block_v=block_v, vocab=V,
                             pad_vocab=pad_v > 0)
    loss, lse = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((Np, _STAT_LANES), jnp.float32),
                   jax.ShapeDtypeStruct((Np, _STAT_LANES), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, E), lambda i, j: (i, 0)),
            pl.BlockSpec((E, block_v), lambda i, j: (0, j)),
        ],
        out_specs=(pl.BlockSpec((block_n, _STAT_LANES),
                                lambda i, j: (i, 0)),) * 2,
        scratch_shapes=[pltpu.VMEM((block_n, _LANES), jnp.float32)] * 3,
        interpret=interpret,
        compiler_params=_kernel_params(interpret),
    )(labp, xp, wp)
    return loss[:N, 0], lse[:N, 0]


def fused_linear_cross_entropy(x, w, labels, *,
                               block_n: Optional[int] = None,
                               block_v: Optional[int] = None,
                               interpret=None):
    """Per-token ``softmax_xent(x @ w, labels)`` without materializing
    logits.

    ``x``: [N, E] activations; ``w``: [E, V] unembedding; ``labels``: [N]
    int.  Returns f32 loss [N].  Differentiable (custom VJP): the backward
    recomputes blockwise probabilities from the saved lse — peak memory is
    O(block_n * block_v + block_n * E + E * block_v) versus the naive
    O(N * V) logits + softmax.  E rides whole in VMEM: sized for LM heads
    (E up to a few thousand), not for E-sharded tensor parallelism — shard
    E outside and psum the partial logits instead if E is huge.
    """
    if interpret is None:
        interpret = _interp()
    from .. import runtime

    block_n, block_v = runtime.resolve_blocks(
        block_n, block_v, "xent_block_n", "xent_block_v")
    block_n, block_v = _fit_blocks(block_n, block_v, x.shape[1],
                                   jnp.dtype(x.dtype).itemsize)
    f = _xent_vjp(x.shape[1], block_n, block_v, interpret)
    return f(x, w, labels)


@functools.lru_cache(maxsize=None)
def _xent_vjp(embed: int, block_n: int, block_v: int, interp_key):
    @jax.custom_vjp
    def f(x, w, labels):
        return _fused_xent_fwd(x, w, labels, block_n, block_v,
                               interp_key)[0]

    def fwd(x, w, labels):
        loss, lse = _fused_xent_fwd(x, w, labels, block_n, block_v,
                                    interp_key)
        return loss, (x, w, labels, lse)

    def bwd(res, dloss):
        x, w, labels, lse = res
        N, E = x.shape
        V = w.shape[1]
        bn = min(block_n, N)
        bv = min(block_v, V)
        xp = _pad_rows(x, bn)
        labp = _pad_rows(labels.astype(jnp.int32)[:, None], bn, fill=-1)
        pad_v = (-V) % bv
        wp = jnp.pad(w, ((0, 0), (0, pad_v))) if pad_v else w
        Np, Vp = xp.shape[0], wp.shape[1]
        # Padded rows: label -1 never matches, and lse=+1e30 makes p == 0,
        # so they contribute nothing to dW (and their dx rows are sliced).
        lse_l = _stats(jnp.where(jnp.isfinite(lse), lse, 0.0), Np)
        lse_l = lse_l.at[N:].set(-NEG_INF) if Np > N else lse_l
        dl_l = _stats(dloss.astype(jnp.float32), Np)

        nn_, nv_ = Np // bn, Vp // bv
        dx_kern = functools.partial(_xent_bwd_dx_kernel, block_n=bn,
                                    block_v=bv, vocab=V,
                                    pad_vocab=pad_v > 0)
        dx = pl.pallas_call(
            dx_kern,
            out_shape=jax.ShapeDtypeStruct((Np, E), jnp.float32),
            grid=(nn_, nv_),
            in_specs=[
                pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
                pl.BlockSpec((bn, E), lambda i, j: (i, 0)),
                pl.BlockSpec((E, bv), lambda i, j: (0, j)),
                pl.BlockSpec((bn, _STAT_LANES), lambda i, j: (i, 0)),
                pl.BlockSpec((bn, _STAT_LANES), lambda i, j: (i, 0)),
            ],
            out_specs=pl.BlockSpec((bn, E), lambda i, j: (i, 0)),
            scratch_shapes=[pltpu.VMEM((bn, E), jnp.float32)],
            interpret=interp_key,
            compiler_params=_kernel_params(interp_key),
        )(labp, xp, wp, lse_l, dl_l)

        dw_kern = functools.partial(_xent_bwd_dw_kernel, block_n=bn,
                                    block_v=bv, vocab=V,
                                    pad_vocab=pad_v > 0)
        dw = pl.pallas_call(
            dw_kern,
            out_shape=jax.ShapeDtypeStruct((E, Vp), jnp.float32),
            grid=(nv_, nn_),
            in_specs=[
                pl.BlockSpec((bn, 1), lambda j, i: (i, 0)),
                pl.BlockSpec((bn, E), lambda j, i: (i, 0)),
                pl.BlockSpec((E, bv), lambda j, i: (0, j)),
                pl.BlockSpec((bn, _STAT_LANES), lambda j, i: (i, 0)),
                pl.BlockSpec((bn, _STAT_LANES), lambda j, i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((E, bv), lambda j, i: (0, j)),
            scratch_shapes=[pltpu.VMEM((E, bv), jnp.float32)],
            interpret=interp_key,
            compiler_params=_kernel_params(interp_key),
        )(labp, xp, wp, lse_l, dl_l)
        if pad_v:
            dw = dw[:, :V]
        return (dx[:N].astype(x.dtype), dw.astype(w.dtype),
                _float0_zero(labels))

    f.defvjp(fwd, bwd)
    return f

"""Pallas TPU kernels: custom collectives over ICI remote DMA, plus hot-op
compute kernels.

``ring`` is the analog of the reference's hand-tuned chunked/pipelined
collective algorithms (SURVEY.md §3 C4: ring/tree over MPI_Isend/Irecv +
CUDA IPC).  On TPU the point-to-point transport is inter-chip RDMA issued
from Pallas kernels; the ring algorithm is the same one the reference
pipelined over MPI p2p.  ``flash`` is the blocked-attention compute kernel
serving the beyond-reference long-context stack.
"""

from . import ring  # noqa: F401  (registers the "pallas" backend)
from .flash import flash_attention  # noqa: F401
from .xent import fused_linear_cross_entropy  # noqa: F401

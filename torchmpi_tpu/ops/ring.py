"""Pallas ring allreduce over ICI inter-chip RDMA.

The TPU-native analog of the reference's custom chunked/pipelined allreduce
(SURVEY.md §3 C4, §4.2 — reconstructed, reference mount empty): where the
reference pipelined MPI_Isend/Irecv rings over chunks with CUDA-IPC intra-node
legs, this kernel drives the ICI links directly with async remote DMA and
double-buffered chunk slots.

Algorithm: classic bandwidth-optimal ring — (n-1) reduce-scatter steps then
(n-1) all-gather steps, each device moving one chunk of ``1/n`` of the tensor
per step, so total bytes-on-wire per device = ``2 (n-1)/n * size`` (the same
bound XLA's allreduce targets; the point of this kernel, as of the
reference's, is a *tunable, inspectable* implementation to benchmark against
the stock one, and a scaffold for fusing compute into collective steps).

Two allreduce schedules exist, selected statically per (shape, chunk_bytes):
the VMEM-resident kernels below stage the whole tensor in VMEM (fastest when
it fits); the CHUNKED kernel (``_ring_allreduce_chunked_kernel``) keeps the
tensor in HBM and streams ``config.chunk_bytes``-sized subchunks through
double-buffered VMEM slots with the next subchunk's RDMA already in flight —
the TPU analog of the reference's pipelined chunk loop (SURVEY.md §4.2), and
the only way a full ResNet-50-sized gradient can ride the custom backend.

Flow-control protocol per step (slot = step % 2):

  1. wait ``ack[slot]`` (skipped for the first two steps): the right
     neighbor has consumed this slot from the previous round, so the remote
     buffer is free — prevents the slot-reuse race in the naive pattern.
  2. RDMA my send-chunk into the right neighbor's ``comm[slot]``;
     ``wait()`` covers both my outgoing send and my incoming chunk
     (symmetric SPMD: every device runs the same step).
  3. combine/copy received chunk; signal ``ack[slot]`` to the left neighbor.

Registered with the selector as backend ``"pallas"`` for allreduce.  Tested
in Pallas TPU interpret mode on the CPU mesh (with ``detect_races=True`` —
the race-detection story, SURVEY.md §6.2) and runnable on real ICI unchanged.
The interpreter caps ring iterations (``_INTERPRET_MAX_ITERS``), so the
production-depth slot/ack protocol is additionally executed at FULL depth —
ResNet-50-gradient plans, C >= 50, adversarial interleavings, mutation
tests — by the pure-numpy schedule simulator in :mod:`.ring_sim`
(tests/test_ring_sim.py).
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import selector

# Chunk granularity: one (8, 128) f32 tile row group.  Chunks are laid out
# [rows, 128]; rows must be a multiple of 8 for clean VMEM tiling.
_LANES = 128
_SUBLANES = 8
_TILE = _LANES * _SUBLANES

# Interpret-mode state: None = auto-detect (interpret on CPU meshes, real
# Mosaic lowering on TPU), False = forced off, InterpretParams = forced on.
_INTERPRET = None

# Cap on total ring iterations (2*(n-1)*C) under the INTERPRETER only.
# Above ~45 the interpreter can deadlock on single-core hosts: each device's
# kernel runs on its own Python thread, but buffer-allocation callbacks block
# in np.array() on XLA-computed initial values, and with one XLA CPU
# execution thread a synchronously-blocking semaphore-wait callback starves
# the executor that would materialize them (observed: dev0 completed all 56
# iterations while 7 peers sat in _allocate_buffer; faulthandler dump in
# docs/ROUND2_NOTES.md).  Real Mosaic lowering has no such limit; when the
# plan exceeds the cap under interpret, subchunks are coarsened (C shrinks,
# sub_elems grows) — the simulated schedule stays chunked, just shallower.
_INTERPRET_MAX_ITERS = 28


class RingInterpretCoarseningWarning(UserWarning):
    """Interpret mode rewrote the configured ``chunk_bytes`` pipeline
    depth to stay inside ``_INTERPRET_MAX_ITERS`` — the executed simulated
    schedule is shallower than the one real TPU lowering will run."""


def set_interpret(params) -> None:
    """Control Pallas TPU interpret mode.

    ``InterpretParams(...)`` forces the interpreter (CPU simulation;
    supports ``detect_races``), ``False`` forces real lowering, ``None``
    restores auto-detection.
    """
    global _INTERPRET
    _INTERPRET = params


def local_kernel_params(interpret):
    """Interpret-mode-only compiler params for DEVICE-LOCAL pallas kernels.

    The pallas TPU interpreter runs an N-party global barrier before
    every kernel that lacks a ``collective_id`` ("the kernel doesn't
    specify its own barrier semaphore").  Device-local kernels (flash,
    fused-xent — in the ring/ulysses stacks the rotation happens OUTSIDE
    the kernel via ppermute) touch no remote memory, so that pre-kernel
    barrier is pure interpreter overhead, and on a starved host it is
    where the flaky full-suite abort parks its threads
    (docs/ROUND4_NOTES.md).  Declaring a collective_id under interpret
    skips it; real TPU lowering is untouched (collective_id there
    allocates a cross-chip barrier semaphore local kernels must not
    claim).  Lives here next to :func:`_interpret_mode`, the shared
    interpret-mode decision point, so the skip logic exists exactly
    once.
    """
    if interpret:
        return pltpu.CompilerParams(collective_id=1)
    return None


def _interpret_mode():
    """Explicit setting wins; in auto mode, enable the interpreter when the
    devices actually executing (the runtime mesh when initialized, else the
    default backend) are CPU — so `--backend pallas` works on simulated
    meshes even on hosts that also have an accelerator attached."""
    if _INTERPRET is not None:
        return _INTERPRET
    try:
        from .. import runtime

        if runtime.is_initialized():
            platform = list(
                runtime.current_mesh().devices.flat)[0].platform
        else:
            platform = jax.default_backend()
        if platform == "cpu":
            if hasattr(pltpu, "InterpretParams"):
                return pltpu.InterpretParams()
            # Older jax (no InterpretParams): the boolean interpreter.
            return True
    except Exception:
        pass
    return False




def _step_indices(my, n: int, s: int, sign: int):
    """Chunk indices for ring step ``s`` (static) in direction ``sign``
    (+1 clockwise / send-right, -1 counter-clockwise / send-left; the ccw
    schedule is the cw one under my -> -my, chunk -> -chunk).  Covers both
    the reduce-scatter phase (s < n-1) and the all-gather phase."""
    if s < n - 1:
        send = lax.rem(my - sign * s + 4 * n, n)
        recv = lax.rem(my - sign * (s + 1) + 4 * n, n)
    else:
        t = s - (n - 1)
        send = lax.rem(my + sign * (1 - t) + 4 * n, n)
        recv = lax.rem(my - sign * t + 4 * n, n)
    return send, recv


def _pad_and_tile(flat, n: int):
    """Pad a flat vector to a multiple of n*TILE and tile as [n, rows, 128]."""
    pad = (-flat.shape[0]) % (n * _TILE)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(n, flat.shape[0] // n // _LANES, _LANES), pad


def runtime_chunk_bytes() -> int:
    from .. import runtime

    return runtime.effective_config().chunk_bytes


def _neighbor_setup(axis: str, mesh_axes, n: int):
    """Shared kernel preamble: ring neighbors, logical-id mapping, and the
    neighbor barrier (both neighbors inside the kernel before any RDMA).
    The subtlest part of these kernels lives in exactly one place."""
    my = lax.axis_index(axis)
    right = lax.rem(my + 1, n)
    left = lax.rem(my + n - 1, n)

    def coords(idx):
        # Flat logical device id of the ring neighbor: other mesh axes keep
        # our own position, the ring axis takes `idx` (row-major over the
        # mesh axis order, which is how LOGICAL ids are assigned).
        lid = jnp.int32(0)
        for a in mesh_axes:
            pos = idx if a == axis else lax.axis_index(a)
            lid = lid * lax.axis_size(a) + pos
        return lid

    bsem = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(bsem, inc=1, device_id=coords(left),
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(bsem, inc=1, device_id=coords(right),
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(bsem, 2)
    return my, left, right, coords


def _ring_allreduce_bidir_kernel(x1_ref, x2_ref, o1_ref, o2_ref,
                                 comm1_ref, comm2_ref,
                                 send1, recv1, ack1,
                                 send2, recv2, ack2,
                                 *, n: int, axis: str,
                                 mesh_axes: Tuple[str, ...]):
    """Bidirectional ring: half 1 rotates clockwise (send right), half 2
    counter-clockwise (send left) — both directions' DMAs are issued before
    either is waited on, so a full-duplex interconnect carries both halves
    concurrently (2x the unidirectional bandwidth bound).

    The schedule is direction-symmetric: in ring-direction space ("next" =
    right for half 1, left for half 2) both halves run the identical
    allreduce schedule of ``_ring_allreduce_kernel``.
    """
    my, left, right, coords = _neighbor_setup(axis, mesh_axes, n)

    o1_ref[...] = x1_ref[...]
    o2_ref[...] = x2_ref[...]

    total_steps = 2 * (n - 1)
    for s in range(total_steps):
        slot = s % 2
        reduce_phase = s < n - 1
        send_idx, recv_idx = _step_indices(my, n, s, +1)
        send_idx2, recv_idx2 = _step_indices(my, n, s, -1)

        if s >= 2:
            pltpu.semaphore_wait(ack1, 1)
            pltpu.semaphore_wait(ack2, 1)

        rdma1 = pltpu.make_async_remote_copy(
            src_ref=o1_ref.at[send_idx], dst_ref=comm1_ref.at[slot],
            send_sem=send1.at[slot], recv_sem=recv1.at[slot],
            device_id=coords(right),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma2 = pltpu.make_async_remote_copy(
            src_ref=o2_ref.at[send_idx2], dst_ref=comm2_ref.at[slot],
            send_sem=send2.at[slot], recv_sem=recv2.at[slot],
            device_id=coords(left),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma1.start()
        rdma2.start()  # both directions in flight before either wait
        rdma1.wait()
        rdma2.wait()

        if reduce_phase:
            o1_ref[recv_idx] = o1_ref[recv_idx] + comm1_ref[slot]
            o2_ref[recv_idx2] = o2_ref[recv_idx2] + comm2_ref[slot]
        else:
            o1_ref[recv_idx] = comm1_ref[slot]
            o2_ref[recv_idx2] = comm2_ref[slot]

        pltpu.semaphore_signal(ack1, inc=1, device_id=coords(left),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(ack2, inc=1, device_id=coords(right),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)

    pltpu.semaphore_wait(ack1, 2)
    pltpu.semaphore_wait(ack2, 2)


def _ring_allreduce_kernel(x_ref, o_ref, comm_ref, send_sem, recv_sem,
                           ack_sem, *, n: int, axis: str,
                           mesh_axes: Tuple[str, ...]):
    """Per-device kernel.  x/o: [n, rows, 128]; comm: [2, rows, 128]."""
    my, left, right, coords = _neighbor_setup(axis, mesh_axes, n)

    o_ref[...] = x_ref[...]

    total_steps = 2 * (n - 1)
    for s in range(total_steps):  # n is static: fully unrolled
        slot = s % 2
        reduce_phase = s < n - 1
        send_idx, recv_idx = _step_indices(my, n, s, +1)

        if s >= 2:
            # Right neighbor must have freed this slot.
            pltpu.semaphore_wait(ack_sem, 1)

        rdma = pltpu.make_async_remote_copy(
            src_ref=o_ref.at[send_idx],
            dst_ref=comm_ref.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=coords(right),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()

        if reduce_phase:
            o_ref[recv_idx] = o_ref[recv_idx] + comm_ref[slot]
        else:
            o_ref[recv_idx] = comm_ref[slot]

        # Tell the left neighbor its copy of this slot is consumed.
        pltpu.semaphore_signal(ack_sem, inc=1, device_id=coords(left),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)

    # Drain outstanding acks so the kernel exits with clean semaphore state:
    # our last two sends were acked by nobody yet... they were: every step
    # sent an ack, but the final two acks from the right neighbor target
    # slots we never rewrite.  Consume them to leave the semaphore at zero.
    pltpu.semaphore_wait(ack_sem, 2)


def _ring_reduce_scatter_kernel(x_ref, o_ref, acc_ref, comm_ref, send_sem,
                                recv_sem, ack_sem, *, n: int, axis: str,
                                mesh_axes: Tuple[str, ...]):
    """RS phase only.  x: [n, rows, 128]; o: [rows, 128] — the fully-reduced
    chunk ``my`` (the schedule is the classic ring shifted by one so each
    device finishes owning its own chunk index)."""
    my, left, right, coords = _neighbor_setup(axis, mesh_axes, n)

    acc_ref[...] = x_ref[...]
    steps = n - 1
    for s in range(steps):
        slot = s % 2
        send_idx, recv_idx = _rs_step_indices(my, n, s)
        if s >= 2:
            pltpu.semaphore_wait(ack_sem, 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=acc_ref.at[send_idx],
            dst_ref=comm_ref.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=coords(right),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        acc_ref[recv_idx] = acc_ref[recv_idx] + comm_ref[slot]
        pltpu.semaphore_signal(ack_sem, inc=1, device_id=coords(left),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(ack_sem, min(2, steps))
    o_ref[...] = acc_ref[my]


def _ring_all_gather_kernel(x_ref, o_ref, comm_ref, send_sem, recv_sem,
                            ack_sem, *, n: int, axis: str,
                            mesh_axes: Tuple[str, ...]):
    """AG only.  x: [rows, 128] (local chunk); o: [n, rows, 128]."""
    my, left, right, coords = _neighbor_setup(axis, mesh_axes, n)

    o_ref[my] = x_ref[...]
    steps = n - 1
    for t in range(steps):
        slot = t % 2
        send_idx = lax.rem(my + n - t, n)
        recv_idx = lax.rem(my + n - t - 1, n)
        if t >= 2:
            pltpu.semaphore_wait(ack_sem, 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=o_ref.at[send_idx],
            dst_ref=comm_ref.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=coords(right),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        o_ref[recv_idx] = comm_ref[slot]
        pltpu.semaphore_signal(ack_sem, inc=1, device_id=coords(left),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(ack_sem, min(2, steps))


def _chunk_plan(nelems: int, n: int, dtype, chunk_bytes: int):
    """Static streaming plan for one device's ring schedule.

    Returns ``(sub_elems, C)``: the tensor pads to ``n * C * sub_elems`` and
    is viewed as ``[n ring chunks, C subchunks, rows, 128]``; each DMA moves
    one ``sub_elems``-element subchunk (~``chunk_bytes`` bytes, TILE-rounded),
    so VMEM residency is 4 double-buffered subchunk slots regardless of
    tensor size.  ``C == 1`` means the whole per-ring-chunk payload fits one
    subchunk and the VMEM-resident kernel is the better schedule.
    """
    ebytes = jnp.dtype(dtype).itemsize
    sub_elems = max(_TILE, (chunk_bytes // ebytes) // _TILE * _TILE)
    per = -(-nelems // n)
    C = max(1, -(-per // sub_elems))
    if C > 1:
        # Rebalance so the last subchunk isn't a sliver of padding.
        sub_elems = -(-per // C)
        sub_elems = -(-sub_elems // _TILE) * _TILE
    return sub_elems, C


def _effective_plan(nelems: int, n: int, dtype, chunk_bytes: int,
                    interpreted: bool, steps: Optional[int] = None):
    """The plan actually executed: under the interpreter the pipeline is
    coarsened so total iterations ``steps * C`` stay within
    ``_INTERPRET_MAX_ITERS`` (see that constant's comment); real Mosaic
    lowering always gets the full plan.  ``steps`` defaults to the
    allreduce schedule's ``2*(n-1)``; the RS/AG-only schedules pass their
    shorter ``n-1`` so their simulated pipelines aren't over-coarsened."""
    if steps is None:
        steps = 2 * (n - 1)
    sub_elems, C = _chunk_plan(nelems, n, dtype, chunk_bytes)
    if interpreted and C > 1:
        # Never coarsen below C=2: a plan that needed chunking must stay
        # chunked (the resident kernel would stage the whole tensor), even
        # on rings wide enough that the iteration cap cannot be honored —
        # the cap is a best-effort wedge guard, the VMEM bound is a
        # guarantee.
        max_c = max(2, _INTERPRET_MAX_ITERS // max(1, steps))
        if C > max_c:
            per = -(-nelems // n)
            configured_c = C
            C = max_c
            per_sub = -(-per // C)
            sub_elems = -(-per_sub // _TILE) * _TILE
            # A knob that silently means something different per platform
            # is dishonest (VERDICT r2 weak #7): say so when the
            # interpreter rewrites the configured schedule.
            warnings.warn(
                f"pallas ring interpret mode coarsened the configured "
                f"chunk_bytes={chunk_bytes} plan from C={configured_c} "
                f"to C={C} subchunks per ring chunk (interpreter "
                f"iteration cap {_INTERPRET_MAX_ITERS} over {steps} "
                f"steps); real TPU lowering executes the full-depth "
                f"plan", RingInterpretCoarseningWarning, stacklevel=3)
    return sub_elems, C


def _rs_step_indices(my, n: int, s: int):
    """Shifted RS schedule (shared by the resident and chunked RS kernels):
    offset by one from the classic ring so each device finishes owning its
    own chunk index."""
    send_idx = lax.rem(my + 2 * n - s - 1, n)
    recv_idx = lax.rem(my + 2 * n - s - 2, n)
    return send_idx, recv_idx


def _chunked_pipeline(work_ref, comm, acc, copy_in, copy_out,
                      send_sem, recv_sem, ack_sem, coords, left, right,
                      *, C: int, steps: int, step_indices, reduce_at):
    """Shared pipelined-subchunk driver for the unidirectional chunked ring
    kernels (allreduce / reduce-scatter / all-gather differ only in step
    count, index schedule, and whether a step reduces or forwards).

    ``work_ref`` is the HBM working buffer ``[n, C, rows, 128]``; comm/acc
    are two-slot VMEM scratch.  Iteration k streams subchunk ``c = k % C``
    of ring step ``s = k // C``:

      - the RDMA for iteration k+1 is issued before iteration k's recv is
        waited on (software pipeline, depth 1), so the next subchunk is on
        the wire while this one is being reduced and written back — the
        HBM->VMEM load of the local addend overlaps the RDMA the same way;
      - subchunks within a step are independent, so the pipeline never
        crosses a true dependency: step s+1 forwards what step s received,
        but subchunk (s+1, c)'s RDMA issues C-1 >= 1 iterations after
        (s, c)'s writeback completed (C > 1 is required; C == 1 plans
        route to the VMEM-resident kernels);
      - slot reuse is flow-controlled by the same neighbor-ack protocol as
        the resident kernels (wait one ack per issue from k >= 2).

    ``step_indices(s) -> (send_idx, recv_idx)``; ``reduce_at(s) -> bool``
    (static Python values — the loop is fully unrolled).
    """
    assert C > 1, "chunked pipeline requires a multi-subchunk plan"
    K = steps * C

    def rdma(k):
        s, c = divmod(k, C)
        send_idx, _ = step_indices(s)
        return pltpu.make_async_remote_copy(
            src_ref=work_ref.at[send_idx, c],
            dst_ref=comm.at[k % 2],
            send_sem=send_sem.at[k % 2],
            recv_sem=recv_sem.at[k % 2],
            device_id=coords(right),
            device_id_type=pltpu.DeviceIdType.LOGICAL)

    def issue(k):
        if k >= 2:
            pltpu.semaphore_wait(ack_sem, 1)
        rdma(k).start()

    issue(0)
    for k in range(K):
        slot = k % 2
        s, c = divmod(k, C)
        _, recv_idx = step_indices(s)
        if k + 1 < K:
            issue(k + 1)
        if reduce_at(s):
            load = pltpu.make_async_copy(work_ref.at[recv_idx, c],
                                         acc.at[slot], copy_in.at[slot])
            load.start()
            rdma(k).wait()
            load.wait()
            acc[slot] = acc[slot] + comm[slot]
            src = acc.at[slot]
        else:
            rdma(k).wait()
            src = comm.at[slot]
        wb = pltpu.make_async_copy(src, work_ref.at[recv_idx, c],
                                   copy_out.at[slot])
        wb.start()
        wb.wait()
        pltpu.semaphore_signal(ack_sem, inc=1, device_id=coords(left),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(ack_sem, min(2, K))


def _ring_allreduce_chunked_kernel(x_ref, o_ref, comm_ref, acc_ref,
                                   copy_in, copy_out, full_sem,
                                   send_sem, recv_sem, ack_sem,
                                   *, n: int, C: int, axis: str,
                                   mesh_axes: Tuple[str, ...]):
    """Chunked/pipelined ring allreduce: the analog of the reference's
    chunk loop (SURVEY.md §4.2 — the performance-critical code upstream).
    Reduce-scatter phase (steps 0..n-2) then all-gather phase; see
    :func:`_chunked_pipeline` for the streaming/flow-control design."""
    my, left, right, coords = _neighbor_setup(axis, mesh_axes, n)

    stage = pltpu.make_async_copy(x_ref, o_ref, full_sem)
    stage.start()
    stage.wait()

    _chunked_pipeline(
        o_ref, comm_ref, acc_ref, copy_in, copy_out,
        send_sem, recv_sem, ack_sem, coords, left, right,
        C=C, steps=2 * (n - 1),
        step_indices=lambda s: _step_indices(my, n, s, +1),
        reduce_at=lambda s: s < n - 1)


def _ring_allreduce_bidir_chunked_kernel(
        x1_ref, x2_ref, o1_ref, o2_ref, comm1, comm2, acc1, acc2,
        copy_in1, copy_in2, copy_out1, copy_out2, full1, full2,
        send1, recv1, ack1, send2, recv2, ack2,
        *, n: int, C: int, axis: str, mesh_axes: Tuple[str, ...]):
    """Bidirectional chunked ring: half 1 streams clockwise (send right),
    half 2 counter-clockwise — per iteration BOTH directions' next RDMAs
    are in flight before either current receive is waited on, so a
    full-duplex interconnect carries both halves concurrently (2x the
    unidirectional bound) while VMEM stays at ~8 subchunk slots.  Each
    direction runs exactly the ``_ring_allreduce_chunked_kernel`` schedule
    (see its docstring for the pipeline/ack reasoning); direction 2 is the
    same schedule under my -> -my."""
    assert C > 1, "chunked kernel requires a multi-subchunk plan"
    my, left, right, coords = _neighbor_setup(axis, mesh_axes, n)

    s1 = pltpu.make_async_copy(x1_ref, o1_ref, full1)
    s2 = pltpu.make_async_copy(x2_ref, o2_ref, full2)
    s1.start()
    s2.start()
    s1.wait()
    s2.wait()

    K = 2 * (n - 1) * C
    refs = ((o1_ref, comm1, acc1, copy_in1, copy_out1, send1, recv1, ack1,
             +1, right, left),
            (o2_ref, comm2, acc2, copy_in2, copy_out2, send2, recv2, ack2,
             -1, left, right))

    def rdma(k, d):
        o_ref, comm, _acc, _ci, _co, send, recv, _ack, sign, to, _frm = refs[d]
        s, c = divmod(k, C)
        send_idx, _ = _step_indices(my, n, s, sign)
        return pltpu.make_async_remote_copy(
            src_ref=o_ref.at[send_idx, c], dst_ref=comm.at[k % 2],
            send_sem=send.at[k % 2], recv_sem=recv.at[k % 2],
            device_id=coords(to),
            device_id_type=pltpu.DeviceIdType.LOGICAL)

    def issue(k):
        if k >= 2:
            pltpu.semaphore_wait(ack1, 1)
            pltpu.semaphore_wait(ack2, 1)
        r1, r2 = rdma(k, 0), rdma(k, 1)
        r1.start()
        r2.start()

    issue(0)
    for k in range(K):
        slot = k % 2
        s, c = divmod(k, C)
        reduce_phase = s < n - 1
        if k + 1 < K:
            issue(k + 1)
        loads = []
        for d in (0, 1):
            o_ref, comm, acc, ci, _co, _s, _r, _a, sign, _to, _frm = refs[d]
            _, recv_idx = _step_indices(my, n, s, sign)
            if reduce_phase:
                load = pltpu.make_async_copy(o_ref.at[recv_idx, c],
                                             acc.at[slot], ci.at[slot])
                load.start()
                loads.append(load)
        rdma(k, 0).wait()
        rdma(k, 1).wait()
        for load in loads:
            load.wait()
        wbs = []
        for d in (0, 1):
            o_ref, comm, acc, _ci, co, _s, _r, _a, sign, _to, _frm = refs[d]
            _, recv_idx = _step_indices(my, n, s, sign)
            if reduce_phase:
                acc[slot] = acc[slot] + comm[slot]
                src = acc.at[slot]
            else:
                src = comm.at[slot]
            wb = pltpu.make_async_copy(src, o_ref.at[recv_idx, c],
                                       co.at[slot])
            wb.start()
            wbs.append(wb)
        for wb in wbs:
            wb.wait()
        pltpu.semaphore_signal(ack1, inc=1, device_id=coords(left),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(ack2, inc=1, device_id=coords(right),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(ack1, min(2, K))
    pltpu.semaphore_wait(ack2, min(2, K))


def _ring_allreduce_bidir_chunked(flat, n: int, axis: str,
                                  mesh_axes: Tuple[str, ...],
                                  sub_elems: int, C: int):
    """flat split in two halves, each padded to [n, C, rows, 128]; both
    stream in opposite directions concurrently."""
    half = flat.shape[0] // 2
    h1, h2 = flat[:half], flat[half:]
    padded = n * C * sub_elems
    L1, L2 = h1.shape[0], h2.shape[0]
    if padded > L1:
        h1 = jnp.concatenate([h1, jnp.zeros((padded - L1,), flat.dtype)])
    if padded > L2:
        h2 = jnp.concatenate([h2, jnp.zeros((padded - L2,), flat.dtype)])
    rows = sub_elems // _LANES
    x1 = h1.reshape(n, C, rows, _LANES)
    x2 = h2.reshape(n, C, rows, _LANES)
    kernel = functools.partial(_ring_allreduce_bidir_chunked_kernel, n=n,
                               C=C, axis=axis, mesh_axes=mesh_axes)
    o1, o2 = pl.pallas_call(
        kernel,
        out_shape=(_out_sds(x1.shape, x1), _out_sds(x2.shape, x2)),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)),
        scratch_shapes=[
            pltpu.VMEM((2, rows, _LANES), x1.dtype),   # comm1
            pltpu.VMEM((2, rows, _LANES), x2.dtype),   # comm2
            pltpu.VMEM((2, rows, _LANES), x1.dtype),   # acc1
            pltpu.VMEM((2, rows, _LANES), x2.dtype),   # acc2
            pltpu.SemaphoreType.DMA((2,)),             # copy_in1
            pltpu.SemaphoreType.DMA((2,)),             # copy_in2
            pltpu.SemaphoreType.DMA((2,)),             # copy_out1
            pltpu.SemaphoreType.DMA((2,)),             # copy_out2
            pltpu.SemaphoreType.DMA(()),               # full1
            pltpu.SemaphoreType.DMA(()),               # full2
            pltpu.SemaphoreType.DMA((2,)),             # send1
            pltpu.SemaphoreType.DMA((2,)),             # recv1
            pltpu.SemaphoreType.REGULAR,               # ack1
            pltpu.SemaphoreType.DMA((2,)),             # send2
            pltpu.SemaphoreType.DMA((2,)),             # recv2
            pltpu.SemaphoreType.REGULAR,               # ack2
        ],
        compiler_params=pltpu.CompilerParams(collective_id=12),
        interpret=_interpret_mode(),
    )(x1, x2)
    f1 = o1.reshape(-1)[:L1]
    f2 = o2.reshape(-1)[:L2]
    return jnp.concatenate([f1, f2])


def _ring_allreduce_chunked(flat, n: int, axis: str,
                            mesh_axes: Tuple[str, ...],
                            sub_elems: int, C: int):
    """flat: 1-D; pads to [n, C, rows, 128] HBM-resident views."""
    L = flat.shape[0]
    padded = n * C * sub_elems
    if padded > L:
        flat = jnp.concatenate([flat, jnp.zeros((padded - L,), flat.dtype)])
    rows = sub_elems // _LANES
    x = flat.reshape(n, C, rows, _LANES)
    kernel = functools.partial(_ring_allreduce_chunked_kernel, n=n, C=C,
                               axis=axis, mesh_axes=mesh_axes)
    out = pl.pallas_call(
        kernel,
        out_shape=_out_sds(x.shape, x),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, rows, _LANES), x.dtype),   # comm slots
            pltpu.VMEM((2, rows, _LANES), x.dtype),   # accumulate slots
            pltpu.SemaphoreType.DMA((2,)),            # copy_in
            pltpu.SemaphoreType.DMA((2,)),            # copy_out
            pltpu.SemaphoreType.DMA(()),              # full staging copy
            pltpu.SemaphoreType.DMA((2,)),            # send
            pltpu.SemaphoreType.DMA((2,)),            # recv
            pltpu.SemaphoreType.REGULAR,              # ack
        ],
        compiler_params=pltpu.CompilerParams(collective_id=11),
        interpret=_interpret_mode(),
    )(x)
    return out.reshape(-1)[:L]


def _ring_reduce_scatter_chunked_kernel(x_ref, o_ref, work_ref, comm, acc,
                                        copy_in, copy_out, full_sem,
                                        send_sem, recv_sem, ack_sem,
                                        *, n: int, C: int, axis: str,
                                        mesh_axes: Tuple[str, ...]):
    """Chunked RS phase only: x/work ``[n, C, rows, 128]`` in HBM, o
    ``[C, rows, 128]`` (the fully-reduced chunk ``my``).  The shared
    :func:`_chunked_pipeline` with the shifted RS schedule."""
    my, left, right, coords = _neighbor_setup(axis, mesh_axes, n)

    stage = pltpu.make_async_copy(x_ref, work_ref, full_sem)
    stage.start()
    stage.wait()

    _chunked_pipeline(
        work_ref, comm, acc, copy_in, copy_out,
        send_sem, recv_sem, ack_sem, coords, left, right,
        C=C, steps=n - 1,
        step_indices=lambda s: _rs_step_indices(my, n, s),
        reduce_at=lambda s: True)

    out = pltpu.make_async_copy(work_ref.at[my], o_ref, full_sem)
    out.start()
    out.wait()


def _ring_all_gather_chunked_kernel(x_ref, o_ref, comm, copy_out, full_sem,
                                    send_sem, recv_sem, ack_sem,
                                    *, n: int, C: int, axis: str,
                                    mesh_axes: Tuple[str, ...]):
    """Chunked AG phase only: x ``[C, rows, 128]`` (local chunk), o
    ``[n, C, rows, 128]`` in HBM.  The shared :func:`_chunked_pipeline`
    with the classic forward schedule and no reduce (received subchunks
    DMA straight from the comm slot to their HBM home; the acc/copy_in
    scratch is never touched, so the resident AG kernel's comm scratch is
    reused in both roles)."""
    my, left, right, coords = _neighbor_setup(axis, mesh_axes, n)

    stage = pltpu.make_async_copy(x_ref, o_ref.at[my], full_sem)
    stage.start()
    stage.wait()

    # AG steps t = 0..n-2 use the classic schedule: send my - t, receive
    # my - t - 1 — exactly _step_indices' reduce-phase formula.
    _chunked_pipeline(
        o_ref, comm, None, None, copy_out,
        send_sem, recv_sem, ack_sem, coords, left, right,
        C=C, steps=n - 1,
        step_indices=lambda t: _step_indices(my, n, t, +1),
        reduce_at=lambda t: False)


def _ring_reduce_scatter_chunked(xin, n: int, axis: str,
                                 mesh_axes: Tuple[str, ...],
                                 sub_elems: int, C: int):
    """xin: [n, per] per-chunk rows; pads per to C*sub_elems."""
    per = xin.shape[1]
    padded = C * sub_elems
    if padded > per:
        xin = jnp.concatenate(
            [xin, jnp.zeros((n, padded - per), xin.dtype)], axis=1)
    rows = sub_elems // _LANES
    x = xin.reshape(n, C, rows, _LANES)
    kernel = functools.partial(_ring_reduce_scatter_chunked_kernel, n=n, C=C,
                               axis=axis, mesh_axes=mesh_axes)
    out = pl.pallas_call(
        kernel,
        out_shape=_out_sds((C, rows, _LANES), x),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.MemorySpace.HBM((n, C, rows, _LANES), x.dtype),  # work
            pltpu.VMEM((2, rows, _LANES), x.dtype),                # comm
            pltpu.VMEM((2, rows, _LANES), x.dtype),                # acc
            pltpu.SemaphoreType.DMA((2,)),                         # copy_in
            pltpu.SemaphoreType.DMA((2,)),                         # copy_out
            pltpu.SemaphoreType.DMA(()),                           # full
            pltpu.SemaphoreType.DMA((2,)),                         # send
            pltpu.SemaphoreType.DMA((2,)),                         # recv
            pltpu.SemaphoreType.REGULAR,                           # ack
        ],
        compiler_params=pltpu.CompilerParams(collective_id=13),
        interpret=_interpret_mode(),
    )(x)
    return out.reshape(-1)[:per]


def _ring_all_gather_chunked(xin, n: int, axis: str,
                             mesh_axes: Tuple[str, ...],
                             sub_elems: int, C: int):
    """xin: [L] local flat chunk; pads to C*sub_elems; returns [n, padded]."""
    L = xin.shape[0]
    padded = C * sub_elems
    if padded > L:
        xin = jnp.concatenate([xin, jnp.zeros((padded - L,), xin.dtype)])
    rows = sub_elems // _LANES
    x = xin.reshape(C, rows, _LANES)
    kernel = functools.partial(_ring_all_gather_chunked_kernel, n=n, C=C,
                               axis=axis, mesh_axes=mesh_axes)
    out = pl.pallas_call(
        kernel,
        out_shape=_out_sds((n, C, rows, _LANES), x),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, rows, _LANES), x.dtype),   # comm
            pltpu.SemaphoreType.DMA((2,)),            # copy_out
            pltpu.SemaphoreType.DMA(()),              # full
            pltpu.SemaphoreType.DMA((2,)),            # send
            pltpu.SemaphoreType.DMA((2,)),            # recv
            pltpu.SemaphoreType.REGULAR,              # ack
        ],
        compiler_params=pltpu.CompilerParams(collective_id=14),
        interpret=_interpret_mode(),
    )(x)
    return out.reshape(n, -1)[:, :L]


def _ring_allreduce_padded(x, n: int, axis: str,
                           mesh_axes: Tuple[str, ...]):
    """x: [n, rows, 128] tiled per device (see _pad_and_tile)."""
    rows = x.shape[1]
    kernel = functools.partial(_ring_allreduce_kernel, n=n, axis=axis,
                               mesh_axes=mesh_axes)
    out = pl.pallas_call(
        kernel,
        out_shape=_out_sds(x.shape, x),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, rows, _LANES), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=pltpu.CompilerParams(collective_id=7),
        interpret=_interpret_mode(),
    )(x)
    return out.reshape(-1)


def _ring_allreduce_bidir_padded(flat, n: int, axis: str,
                                 mesh_axes: Tuple[str, ...]):
    """flat split in two halves, each padded to n*TILE; both ring in
    opposite directions concurrently."""
    half = flat.shape[0] // 2
    h1, h2 = flat[:half], flat[half:]

    x1, pad1 = _pad_and_tile(h1, n)
    x2, pad2 = _pad_and_tile(h2, n)
    kernel = functools.partial(_ring_allreduce_bidir_kernel, n=n, axis=axis,
                               mesh_axes=mesh_axes)
    o1, o2 = pl.pallas_call(
        kernel,
        out_shape=(_out_sds(x1.shape, x1), _out_sds(x2.shape, x2)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        scratch_shapes=[
            pltpu.VMEM((2,) + x1.shape[1:], x1.dtype),
            pltpu.VMEM((2,) + x2.shape[1:], x2.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=pltpu.CompilerParams(collective_id=10),
        interpret=_interpret_mode(),
    )(x1, x2)
    f1 = o1.reshape(-1)
    f2 = o2.reshape(-1)
    if pad1:
        f1 = f1[:f1.shape[0] - pad1]
    if pad2:
        f2 = f2[:f2.shape[0] - pad2]
    return jnp.concatenate([f1, f2])


_SUPPORTED_DTYPES = (jnp.float32, jnp.bfloat16, jnp.int32)


def ring_allreduce(x, axis_names, *, op: str = "sum"):
    """Selector-registered entry: allreduce over the *last* axis in
    ``axis_names`` with the ring kernel; any leading axes (e.g. ``dcn``) are
    reduced with a stock psum afterwards (hierarchical composition).

    Schedule selection (all static, so ``set_config(chunk_bytes=...)``
    recompiles and genuinely changes the schedule):

    - per-ring-chunk payload > ``config.chunk_bytes``: the chunked/pipelined
      kernel streams subchunks HBM->VMEM with the next RDMA in flight —
      VMEM use is bounded by ~4x chunk_bytes however large the tensor;
    - otherwise ``config.pallas_bidirectional`` and size permitting: the
      VMEM-resident bidirectional kernel (halves ring in opposite
      directions, 2x bandwidth bound on full-duplex ICI links);
    - otherwise: the VMEM-resident unidirectional kernel.

    Supported dtypes: f32, bf16, i32; anything else raises (no silent
    downcast — a backend swap must never change numerics).
    """
    if op not in ("sum", "mean"):
        raise KeyError(f"pallas ring allreduce does not support op {op!r}")
    axes = (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)
    ring_axis = axes[-1]
    outer_axes = axes[:-1]
    n = lax.axis_size(ring_axis)

    # Logical device ids need the coordinates over ALL mesh axes of the
    # enclosing shard_map, not just the ring axis; see _mesh_axes_for.
    mesh_axes = _mesh_axes_for(axes)

    from .. import runtime

    cfg = runtime.effective_config()
    bidir = cfg.pallas_bidirectional
    chunk_bytes = cfg.chunk_bytes

    if n == 1:
        out = x
    else:
        shape, dtype = x.shape, x.dtype
        if dtype not in _SUPPORTED_DTYPES:
            raise TypeError(
                f"pallas ring allreduce supports f32/bf16/i32, got {dtype} "
                f"(use the xla backend for other dtypes)")
        flat = x.reshape(-1)
        interp = bool(_interpret_mode())
        sub_elems, C = _effective_plan(flat.shape[0], n, dtype, chunk_bytes,
                                       interp)
        if C > 1:
            half_plan = _effective_plan(-(-flat.shape[0] // 2), n, dtype,
                                        chunk_bytes, interp)
            if bidir and half_plan[1] > 1:
                reduced = _ring_allreduce_bidir_chunked(
                    flat, n, ring_axis, mesh_axes, *half_plan)
            else:
                reduced = _ring_allreduce_chunked(flat, n, ring_axis,
                                                  mesh_axes, sub_elems, C)
        elif bidir and flat.shape[0] >= 2 * n * _TILE:
            reduced = _ring_allreduce_bidir_padded(flat, n, ring_axis,
                                                   mesh_axes)
        else:
            tiled, pad = _pad_and_tile(flat, n)
            reduced = _ring_allreduce_padded(tiled, n, ring_axis, mesh_axes)
            if pad:
                reduced = reduced[:reduced.shape[0] - pad]
        out = reduced.reshape(shape).astype(dtype)
    for a in outer_axes:
        out = lax.psum(out, a)
    if op == "mean":
        total = n
        for a in outer_axes:
            total *= lax.axis_size(a)
        out = out / total
    return out


selector.register("allreduce", "pallas", ring_allreduce)


def _mesh_axes_for(axes: Tuple[str, ...]) -> Tuple[str, ...]:
    """All mesh axis names of the enclosing shard_map, in mesh order —
    logical device ids are row-major over the FULL mesh, so the neighbor
    computation needs every axis, not just the ring axes.  Uses the public
    abstract-mesh accessor; falls back to the ring axes when tracing
    outside any mesh (e.g. direct kernel unit tests)."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
        mesh_axes = tuple(mesh.axis_names) if mesh is not None else axes
    except Exception:
        mesh_axes = axes
    if not all(a in mesh_axes for a in axes):
        mesh_axes = axes
    return mesh_axes


def _out_sds(shape, x):
    try:
        vma = jax.typeof(x).vma
    except Exception:
        vma = None
    return (jax.ShapeDtypeStruct(shape, x.dtype, vma=vma)
            if vma else jax.ShapeDtypeStruct(shape, x.dtype))


def ring_reduce_scatter(x, axis_names, *, op: str = "sum"):
    """Ring reduce-scatter over the last axis of ``axis_names``, with the
    same tiled semantics as the stock backend (``lax.psum_scatter`` with
    ``scatter_dimension=0, tiled=True``): input ``[k, ...]`` with ``k``
    divisible by the group size yields output ``[k/group, ...]`` — whole
    leading-dim rows, so selector fallback between backends never changes
    the output shape.

    Composition order for multi-axis groups: the outer (dcn) axes are
    psum_scatter'd with the stock path FIRST, then the remaining slice is
    ring-scattered over ICI — combined-rank order is outer-major, so device
    (d, i) ends with global slice ``d*n + i``."""
    if op != "sum":
        raise KeyError(f"pallas ring reduce_scatter supports sum, not {op!r}")
    axes = (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)
    ring_axis = axes[-1]
    outer_axes = axes[:-1]
    n = lax.axis_size(ring_axis)
    mesh_axes = _mesh_axes_for(axes)
    total = n
    for a in outer_axes:
        total *= lax.axis_size(a)
    if x.shape[0] % total != 0:
        raise ValueError(
            f"reduce_scatter needs leading dim divisible by group size: "
            f"{x.shape[0]} % {total}")
    out_shape = (x.shape[0] // total,) + x.shape[1:]
    for a in outer_axes:
        x = x.reshape((-1,) + x.shape[1:])
        x = lax.psum_scatter(x, a, scatter_dimension=0, tiled=True)
    flat = x.reshape(-1)
    L = flat.shape[0]
    per = L // n
    chunks = flat.reshape(n, per)
    if n == 1:
        return chunks[0].reshape(out_shape)
    sub_elems, C = _effective_plan(L, n, flat.dtype,
                                   runtime_chunk_bytes(),
                                   bool(_interpret_mode()), steps=n - 1)
    if C > 1:
        out = _ring_reduce_scatter_chunked(chunks, n, ring_axis, mesh_axes,
                                           sub_elems, C)
        return out.reshape(out_shape)
    pad = (-per) % _TILE
    if pad:
        chunks = jnp.concatenate(
            [chunks, jnp.zeros((n, pad), flat.dtype)], axis=1)
    rows = (per + pad) // _LANES
    xin = chunks.reshape(n, rows, _LANES)
    kernel = functools.partial(_ring_reduce_scatter_kernel, n=n,
                               axis=ring_axis, mesh_axes=mesh_axes)
    out = pl.pallas_call(
        kernel,
        out_shape=_out_sds((rows, _LANES), xin),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((n, rows, _LANES), xin.dtype),
            pltpu.VMEM((2, rows, _LANES), xin.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=pltpu.CompilerParams(collective_id=8),
        interpret=_interpret_mode(),
    )(xin)
    return out.reshape(-1)[:per].reshape(out_shape)


def ring_all_gather(x, axis_names):
    """Ring all-gather over the last axis; output stacks ring members on a
    new leading axis (matching ``lax.all_gather(axis=0, tiled=False)``),
    then outer axes are gathered with the stock path and flattened so the
    leading axis is the full (row-major) rank order."""
    axes = (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)
    ring_axis = axes[-1]
    outer_axes = axes[:-1]
    n = lax.axis_size(ring_axis)
    mesh_axes = _mesh_axes_for(axes)
    shape = x.shape
    flat = x.reshape(-1)
    L = flat.shape[0]
    sub_elems, C = _effective_plan(L * n, n, flat.dtype,
                                   runtime_chunk_bytes(),
                                   bool(_interpret_mode()), steps=n - 1)
    if n > 1 and C > 1:
        gathered = _ring_all_gather_chunked(flat, n, ring_axis, mesh_axes,
                                            sub_elems, C)
        out = gathered.reshape((n,) + shape)
        for a in reversed(outer_axes):
            out = lax.all_gather(out, a, axis=0, tiled=False)
            out = out.reshape((-1,) + shape)
        return out
    pad = (-L) % _TILE
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    rows = flat.shape[0] // _LANES
    xin = flat.reshape(rows, _LANES)
    if n == 1:
        gathered = xin[None]
    else:
        kernel = functools.partial(_ring_all_gather_kernel, n=n,
                                   axis=ring_axis, mesh_axes=mesh_axes)
        gathered = pl.pallas_call(
            kernel,
            out_shape=_out_sds((n, rows, _LANES), xin),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((2, rows, _LANES), xin.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR,
            ],
            compiler_params=pltpu.CompilerParams(collective_id=9),
            interpret=_interpret_mode(),
        )(xin)
    out = gathered.reshape(n, -1)[:, :L].reshape((n,) + shape)
    for a in reversed(outer_axes):
        out = lax.all_gather(out, a, axis=0, tiled=False)
        out = out.reshape((-1,) + shape)
    return out


selector.register("reduce_scatter", "pallas", ring_reduce_scatter)
selector.register("allgather", "pallas", ring_all_gather)

"""Depth-faithful schedule simulator for the chunked ring kernels.

VERDICT r4 #4: the pallas TPU interpreter caps total ring iterations at
``ring._INTERPRET_MAX_ITERS`` (28) on single-core hosts, so the
production-depth double-buffer + ack protocol — the part of
:mod:`.ring` most like the reference's pipelined chunk loop (SURVEY.md
§4.2) — had only ever been validated by AOT lowering, never by an
executed schedule.  This module executes the EXACT slot/ack protocol of
:func:`.ring._chunked_pipeline` in pure numpy: one state machine per
device running the same iteration sequence as the kernel (issue ->
pipelined next-issue -> wait -> combine/copy -> writeback -> ack), with
no interpreter threads and no iteration cap, driven by an arbitrary
scheduler (randomized or adversarial interleavings).

The simulator is STRICTER than hardware in three ways:

- **slot-overwrite hazard**: an RDMA delivery into a comm slot whose
  previous payload the receiver has not consumed yet raises
  :class:`HazardError`.  Delivery is modeled at RDMA *start* — the
  earliest point real hardware could write — so any interleaving the
  protocol permits that COULD corrupt under some link timing is caught,
  not just ones that happen to corrupt under one timing.
- **source-mutation hazard**: a writeback into the HBM region an
  in-flight outgoing RDMA is still reading raises :class:`HazardError`
  (the pipelined ``issue(k+1)``-before-``writeback(k)`` overlap is safe
  only because their regions are provably disjoint — this check proves
  it on every executed schedule instead of by argument).
- **deadlock**: a state where no device can advance raises
  :class:`DeadlockError` with each device's progress and blocked event.

Numerics are asserted by the tests against closed-form numpy reductions.
The per-subchunk payload width does not enter the protocol (indices,
slots, and acks depend only on ``(n, C, steps)``), so tests may shrink
``sub_elems`` to keep production-depth ``C`` cheap while taking the real
``(sub_elems, C)`` plan from :func:`.ring._chunk_plan` for the
plan-parity assertions.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np


class HazardError(AssertionError):
    """A data race the flow-control protocol is supposed to prevent."""


class DeadlockError(AssertionError):
    """No device can advance; carries the stuck per-device state."""


def step_indices_allreduce(my: int, n: int, s: int, sign: int = 1):
    """Pure-python mirror of :func:`.ring._step_indices` (same formulas,
    ``lax.rem`` replaced by ``%``): reduce-scatter phase for
    ``s < n - 1``, all-gather phase after."""
    if s < n - 1:
        return (my - sign * s) % n, (my - sign * (s + 1)) % n
    t = s - (n - 1)
    return (my + sign * (1 - t)) % n, (my - sign * t) % n


def step_indices_rs(my: int, n: int, s: int):
    """Mirror of :func:`.ring._rs_step_indices` (the shifted RS schedule
    under which each device finishes owning its own chunk index)."""
    return (my - s - 1) % n, (my - s - 2) % n


def _device_program(K: int, use_acks: bool):
    """The event sequence of one device in ring._chunked_pipeline,
    expressed as the generator of blocking/effectful events the
    scheduler interprets.  Mirrors the kernel line for line: issue(0);
    then for each k: issue(k+1) BEFORE waiting k (the software
    pipeline), wait k, combine+writeback, ack; finally drain."""

    def issue(k):
        if use_acks and k >= 2:
            yield ("ack_wait", 1)
        yield ("rdma_start", k)

    yield from issue(0)
    for k in range(K):
        if k + 1 < K:
            yield from issue(k + 1)
        yield ("rdma_wait", k)
        yield ("writeback", k)
        yield ("signal_ack",)
    if use_acks:
        yield ("ack_wait", min(2, K))


def simulate(work0: List[np.ndarray], C: int, steps: int,
             step_indices: Callable[[int, int], Tuple[int, int]],
             reduce_at: Callable[[int], bool], *, sign: int = 1,
             scheduler: str = "random",
             rng: Optional[np.random.RandomState] = None,
             use_acks: bool = True,
             starve: Optional[int] = None) -> List[np.ndarray]:
    """Run the chunked-ring schedule to completion and return the final
    per-device work buffers.

    ``work0[d]`` is device d's HBM working buffer ``[n, C, sub]``
    (mutated in place on a copy); ``step_indices(d, s)`` maps a device
    and ring step to its (send_idx, recv_idx) chunk pair; ``sign``
    selects the neighbor direction (+1 send-right as the cw kernels do,
    -1 the ccw half of the bidirectional kernel).  ``scheduler``:
    "random" picks uniformly among runnable devices per event (pass
    ``rng``), "greedy" always advances the lowest-index runnable device.
    ``starve=d`` refuses to schedule device d while any other device is
    runnable (the adversarial interleaving that makes a missing-ack
    protocol fail fast).  ``use_acks=False`` runs the MUTATED protocol
    with the ack waits removed — used by tests to prove the hazard
    detectors actually fire."""
    n = len(work0)
    K = steps * C
    work = [w.copy() for w in work0]
    rng = rng or np.random.RandomState(0)

    right = [(d + sign) % n for d in range(n)]
    left = [(d - sign) % n for d in range(n)]

    ack = [0] * n
    # comm slot state per device: pending iteration (None = free/consumed)
    # and the payload itself.
    comm_pending: List[List[Optional[int]]] = [[None, None]
                                               for _ in range(n)]
    comm_data = [[None, None] for _ in range(n)]
    delivered = [set() for _ in range(n)]   # iterations arrived at d
    inflight_out = [dict() for _ in range(n)]  # k -> (send_idx, c)

    progs = [_device_program(K, use_acks) for _ in range(n)]
    current = [next(p) for p in progs]
    done = [False] * n

    def runnable(d):
        ev = current[d]
        if ev[0] == "ack_wait":
            return ack[d] >= ev[1]
        if ev[0] == "rdma_wait":
            return ev[1] in delivered[d]
        return True  # rdma_start / writeback / signal_ack are immediate

    def execute(d):
        ev = current[d]
        kind = ev[0]
        if kind == "ack_wait":
            ack[d] -= ev[1]
        elif kind == "rdma_start":
            k = ev[1]
            s, c = divmod(k, C)
            send_idx, _ = step_indices(d, s)
            slot = k % 2
            tgt = right[d]
            if comm_pending[tgt][slot] is not None:
                raise HazardError(
                    f"slot overwrite: device {d} iteration {k} delivers "
                    f"into device {tgt} comm[{slot}] while its iteration "
                    f"{comm_pending[tgt][slot]} payload is unconsumed "
                    f"(n={n}, C={C}, steps={steps})")
            comm_data[tgt][slot] = work[d][send_idx, c].copy()
            comm_pending[tgt][slot] = k
            delivered[tgt].add(k)
            inflight_out[d][k] = (send_idx, c)
        elif kind == "rdma_wait":
            # Send side of the same descriptor: the DMA read of our
            # source region is complete once wait() returns.
            inflight_out[d].pop(ev[1], None)
        elif kind == "writeback":
            k = ev[1]
            s, c = divmod(k, C)
            _, recv_idx = step_indices(d, s)
            slot = k % 2
            for k2, (si, ci) in inflight_out[d].items():
                if (si, ci) == (recv_idx, c):
                    raise HazardError(
                        f"source mutation: device {d} iteration {k} "
                        f"writes work[{recv_idx},{c}] while its "
                        f"iteration {k2} RDMA still reads it")
            val = comm_data[d][slot]
            if reduce_at(s):
                work[d][recv_idx, c] = work[d][recv_idx, c] + val
            else:
                work[d][recv_idx, c] = val
            comm_pending[d][slot] = None  # slot free for the next round
        elif kind == "signal_ack":
            ack[left[d]] += 1
        else:  # pragma: no cover - defensive
            raise ValueError(f"unknown event {ev!r}")
        try:
            current[d] = next(progs[d])
        except StopIteration:
            done[d] = True

    while not all(done):
        ready = [d for d in range(n) if not done[d] and runnable(d)]
        if starve is not None:
            others = [d for d in ready if d != starve]
            if others:
                ready = others
        if not ready:
            state = {d: ("done" if done[d] else current[d])
                     for d in range(n)}
            raise DeadlockError(
                f"no runnable device (n={n}, C={C}, steps={steps}, "
                f"acks={use_acks}): {state}; ack counts {ack}")
        if scheduler == "greedy":
            d = ready[0]
        else:
            d = ready[int(rng.randint(len(ready)))]
        execute(d)

    if use_acks and any(a != 0 for a in ack):
        raise HazardError(
            f"semaphores not drained at exit: ack counts {ack} "
            f"(kernel contract: every device leaves its ack at zero)")
    return work


def simulate_allreduce(x: np.ndarray, C: int, **kw) -> List[np.ndarray]:
    """Chunked ring allreduce at depth C.  ``x``: [n, n, C, sub] —
    device d's initial buffer is ``x[d]``.  Returns the n final
    buffers (each should equal ``x.sum(0)``)."""
    n = x.shape[0]
    sign = kw.get("sign", 1)
    return simulate(
        [x[d] for d in range(n)], C, 2 * (n - 1),
        lambda d, s: step_indices_allreduce(d, n, s, sign),
        lambda s: s < n - 1, **kw)


def simulate_reduce_scatter(x: np.ndarray, C: int, **kw) -> np.ndarray:
    """Chunked RS phase: returns [n, C, sub] where row d is device d's
    owned reduced chunk (work[d][d] after the shifted schedule)."""
    n = x.shape[0]
    out = simulate(
        [x[d] for d in range(n)], C, n - 1,
        lambda d, s: step_indices_rs(d, n, s),
        lambda s: True, **kw)
    return np.stack([out[d][d] for d in range(n)])


def simulate_all_gather(chunks: np.ndarray, C: int, **kw) -> List[np.ndarray]:
    """Chunked AG phase: ``chunks`` [n, C, sub] (device d's local
    chunk); device d's work starts as zeros except work[d] = chunks[d].
    Every final buffer should equal ``chunks``."""
    n = chunks.shape[0]
    work0 = []
    for d in range(n):
        w = np.zeros((n,) + chunks.shape[1:], chunks.dtype)
        w[d] = chunks[d]
        work0.append(w)
    return simulate(
        work0, C, n - 1,
        lambda d, t: step_indices_allreduce(d, n, t, 1),
        lambda t: False, **kw)

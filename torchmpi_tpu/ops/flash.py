"""Pallas TPU flash attention: the hot-op kernel for the compute path.

The reference has no attention anywhere (pre-transformer, SURVEY.md §6.7);
this kernel serves the beyond-reference long-context stack
(parallel/sequence.py, models/transformer.py) the TPU-first way: blocked
online-softmax attention that never materializes the [T, T] score matrix,
streaming K/V blocks through VMEM while the accumulator lives in VMEM
scratch across grid steps.  MXU-friendly: both matmuls per block are
[block_q, D] x [D, block_k] and [block_q, block_k] x [block_k, D] with f32
accumulation (``preferred_element_type``), bf16-ready inputs.

Why scratch-across-grid works: the TPU grid is executed sequentially with
the last dimension minor, so the (m, l, acc) scratch carries the running
softmax state across the k-block dimension for one (batch, head, q-block)
triple, exactly the flash-attention recurrence.

``q_offset``/``kv_offset`` place the local q and kv blocks at global
sequence positions, so the same kernel computes the shard-diagonal causal
block of ring attention (parallel/sequence.py) where q and kv start at
different global offsets.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Finite stand-in for -inf in masked scores: keeps exp() exactly 0 without
# producing (-inf) - (-inf) = nan in the running-max rescale.
_NEG_INF = -1e30

# Lane width: m/l scratch rows are stored broadcast across a full 128-lane
# vector so every read/write is a full-tile op (same layout the TPU flash
# kernels in jax use); per-row values are recovered with a lane-reduce.
_LANES = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale: float, causal: bool, q_offset: int, kv_offset: int,
                  block_q: int, block_k: int, kv_len: int):
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0]  # [block_q, D]
    k = k_ref[0, 0]  # [block_k, D]
    v = v_ref[0, 0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [block_q, block_k]

    i = pl.program_id(2)
    k_global = kv_offset + j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = k_global < kv_offset + kv_len  # mask K/V padding rows
    if causal:
        q_global = q_offset + i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        valid = jnp.logical_and(valid, q_global >= k_global)
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = jnp.max(m_ref[:], axis=1, keepdims=True)  # [block_q, 1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # Fully-masked-so-far rows have m_new == _NEG_INF; exponentiate against
    # 0 there so masked scores give p == 0, not exp(-1e30 + 1e30) == 1.
    m_safe = jnp.where(m_new > 0.5 * _NEG_INF, m_new, 0.0)
    alpha = jnp.exp(m_prev - m_safe)  # 0 when m_prev is _NEG_INF (init)
    p = jnp.exp(s - m_safe)  # masked entries: exp(_NEG_INF) == 0
    l_prev = jnp.max(l_ref[:], axis=1, keepdims=True)
    l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        # Fully-masked rows (l == 0) read as zeros, matching the parallel
        # variants' convention in parallel/sequence.py.
        denom = jnp.where(l_new > 0, l_new, 1.0)
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None, q_offset: int = 0,
                    kv_offset: int = 0, block_q: int = 128,
                    block_k: int = 128, interpret=None):
    """Blocked flash attention on one device.

    ``q``: [B, T_q, H, D]; ``k``/``v``: [B, T_kv, H, D] (the bqhd layout of
    parallel/sequence.py).  Returns [B, T_q, H, D] in ``q``'s dtype.

    ``q_offset``/``kv_offset`` are the global positions of ``q[:, 0]`` and
    ``k[:, 0]`` for causal masking (both 0 for plain self-attention); the
    offsets let one kernel serve sequence-sharded callers.  Numerics match
    :func:`parallel.sequence.reference_attention` to dtype tolerance; the
    [T_q, T_kv] score matrix never exists in memory — VMEM residency is
    O(block_q * block_k + block_q * D) per (batch, head).
    """
    B, Tq, H, D = q.shape
    Tkv = k.shape[1]
    if k.shape != (B, Tkv, H, D) or v.shape != k.shape:
        raise ValueError(f"shape mismatch: q {q.shape} k {k.shape} "
                         f"v {v.shape}")
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    block_q = min(block_q, Tq)
    block_k = min(block_k, Tkv)
    pad_q = (-Tq) % block_q
    pad_k = (-Tkv) % block_k
    qt = jnp.moveaxis(q, 2, 1)  # [B, H, Tq, D]
    kt = jnp.moveaxis(k, 2, 1)
    vt = jnp.moveaxis(v, 2, 1)
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    nq = qt.shape[2] // block_q
    nk = kt.shape[2] // block_k

    if interpret is None:
        from . import ring

        interpret = ring._interpret_mode()

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, q_offset=q_offset,
        kv_offset=kv_offset, block_q=block_q, block_k=block_k, kv_len=Tkv)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i, j: (b, h, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom
            pltpu.VMEM((block_q, D), jnp.float32),       # output accum
        ],
        interpret=interpret,
    )(qt, kt, vt)
    if pad_q:
        out = out[:, :, :Tq]
    return jnp.moveaxis(out, 1, 2)

"""Pallas TPU flash attention: the hot-op kernel for the compute path.

The reference has no attention anywhere (pre-transformer, SURVEY.md §6.7);
this kernel serves the beyond-reference long-context stack
(parallel/sequence.py, models/transformer.py) the TPU-first way: blocked
online-softmax attention that never materializes the [T, T] score matrix,
streaming K/V blocks through VMEM while the accumulator lives in VMEM
scratch across grid steps.  MXU-friendly: both matmuls per block are
[block_q, D] x [D, block_k] and [block_q, block_k] x [block_k, D] with f32
accumulation (``preferred_element_type``), bf16-ready inputs.

Why scratch-across-grid works: the TPU grid is executed sequentially with
the last dimension minor, so the (m, l, acc) scratch carries the running
softmax state across the k-block dimension for one (batch, head, q-block)
triple, exactly the flash-attention recurrence.

``q_offset``/``kv_offset`` place the local q and kv blocks at global
sequence positions and may be TRACED scalars (they ride in SMEM), so the
same kernel computes ring attention's per-step blocks inside ``shard_map``
where the kv owner — hence its offset — depends on ``lax.axis_index``.
``return_residuals=True`` returns the un-normalized numerator plus the
(m, l) softmax statistics, the contract ring attention's cross-block
combiner needs (parallel/sequence.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import ring

# Finite stand-in for -inf in masked scores: keeps exp() exactly 0 without
# producing (-inf) - (-inf) = nan in the running-max rescale.
NEG_INF = -1e30

# Lane width of the VMEM m/l scratch: rows are stored broadcast across a
# full 128-lane vector so every read/write is a full-tile op (same layout
# the TPU flash kernels in jax use); per-row values are recovered with a
# lane-reduce.
_LANES = 128

# Lane width of the (optional) m/l residual OUTPUTS: 8 lanes keep the HBM
# footprint at Tq*8 floats per (batch, head) instead of Tq*128 while still
# writing full rows of the f32 (8, 128)-tile layout.
_STAT_LANES = 8



def _flash_params(interpret):
    """Compiler params for the flash kernels.  Interpret: the device-
    local barrier skip (ring.local_kernel_params).  Real Mosaic
    lowering: mark the (batch, head, major-block) grid dims ``parallel``
    and only the minor accumulation dim ``arbitrary`` — the scratch
    state carries ONLY across the minor dim (re-initialized at its
    first step), so declaring the outer dims parallel is sound and lets
    Mosaic schedule/pipeline across grid steps instead of assuming a
    serial carried dependency (the jax TPU flash kernels mark their
    grids the same way)."""
    if interpret:
        return ring.local_kernel_params(interpret)
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"))


def _resolve_blocks(block_a, block_b, field_a: str, field_b: str):
    """Config-default tiling resolution — see runtime.resolve_blocks
    (deferred import: ops must stay importable before the runtime)."""
    from .. import runtime

    return runtime.resolve_blocks(block_a, block_b, field_a, field_b)


def _prescale_enabled() -> bool:
    """``Config.flash_prescale`` (see config.py): fold the attention
    scale into q once instead of scaling every score block."""
    from .. import runtime

    return bool(runtime.effective_config().flash_prescale)


def _prescale_q(q, scale):
    """q' = q * scale in q's dtype — one [B, T, H, D] pass replacing a
    [block_q, block_k] pass per live block inside the kernel."""
    return (q.astype(jnp.float32) * scale).astype(q.dtype)


def _block_live(qo_ref, ko_ref, i, j, block_q: int, block_k: int,
                kv_len: int, causal: bool, window: Optional[int] = None):
    """Scalar predicate: does block (i, j) have ANY valid score?  The
    block-granular complement of :func:`_valid_mask` — a block is dead
    when its first k position is past the last q row (causal), when its
    last k position is before the oldest key the block's FIRST q row may
    see (sliding ``window`` — the first q row reaches furthest back), or
    when it is past the kv length.  The
    kv-length clause is purely defensive — callers pad by less than one
    block, so the last k block always holds >=1 valid key and in-block
    padding exclusion is _valid_mask's job.  Offsets are traced SMEM
    scalars (ring attention), so this is a runtime predicate, not grid
    pruning; for causal self-attention it halves the compute, and with a
    window the live band is O(window/block_k) blocks per q block — the
    kernel's cost becomes O(T * window) regardless of T.  Forward and
    backward kernels MUST skip identically, so all of them call this one
    helper."""
    k_first = ko_ref[0] + j * block_k
    live = k_first < ko_ref[0] + kv_len
    if causal:
        q_first = qo_ref[0] + i * block_q
        live = jnp.logical_and(live, k_first <= q_first + (block_q - 1))
        if window is not None:
            # The OLDEST q row in the block (q_first) reaches furthest
            # back: it sees keys >= q_first - (window - 1).  A k block
            # whose last key is older than that serves no q row here.
            live = jnp.logical_and(
                live, k_first + (block_k - 1) >= q_first - (window - 1))
    return live


def _block_full(qo_ref, ko_ref, i, j, block_q: int, block_k: int,
                kv_len: int, causal: bool, window: Optional[int] = None):
    """Scalar predicate: does block (i, j) have NO masked score at all?
    The complement question to :func:`_block_live` — a block is FULL when
    every (q row, k col) pair is valid: the k block sits entirely inside
    the kv length, entirely in the causal past of the block's OLDEST q
    row (k_last <= q_first), and (sliding window) entirely inside the
    window of the block's NEWEST q row (q_last - k_first < window).

    Why it exists (VERDICT r4 #2): the per-block VPU work — two iotas,
    compares, logical-ands and a [block_q, block_k] select — costs more
    than the block's two MXU matmuls at production shapes, and for
    causal T=4096 at 512x512 blocks ~78% of live blocks are interior
    (mask all-true).  Splitting the update into a full path (no mask
    math) and a partial path keeps numerics bit-identical: on a full
    block the mask is the identity.  Forward and backward kernels share
    this ONE predicate so they specialize identically."""
    k_first = ko_ref[0] + j * block_k
    k_last = k_first + (block_k - 1)
    full = k_last < ko_ref[0] + kv_len
    if causal:
        q_first = qo_ref[0] + i * block_q
        full = jnp.logical_and(full, k_last <= q_first)
        if window is not None:
            q_last = q_first + (block_q - 1)
            full = jnp.logical_and(full, q_last - k_first < window)
    return full


def _gqa_group(h: int, h_kv: int) -> int:
    """Query-heads-per-kv-head (grouped-query attention).  1 == MHA;
    kv head for q head ``h`` is ``h // group`` (the jnp.repeat layout)."""
    if h_kv == h:
        return 1
    if h_kv < 1 or h % h_kv != 0:
        raise ValueError(f"num q heads {h} must be a multiple of kv "
                         f"heads {h_kv}")
    return h // h_kv


def _check_window(window: Optional[int], causal: bool) -> None:
    """Sliding windows are defined over causal order: ``window`` counts
    the query itself plus the ``window - 1`` keys before it."""
    if window is None:
        return
    if not causal:
        raise ValueError("window= requires causal=True (a sliding window "
                         "is defined over causal order)")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def _clamp_block(block: int, t: int, align: int = 128) -> int:
    """Clamp a config-default block size to a sequence of length ``t``
    without producing tile-unaligned block shapes: a block larger than
    ``t`` becomes ``t`` rounded UP to ``align`` (the input is then padded
    to one full block), never a raw ``min(block, t)`` that Mosaic may
    refuse to tile (e.g. t=300).  Explicit caller-passed blocks <= t are
    respected as-is."""
    if block >= t:
        return -(-t // align) * align
    return block



def _kv_band_start(i, *, qo: int, ko: int, window: int, block_q: int,
                   block_k: int, nk: int, n_band: int):
    """First kv-block index of q-block ``i``'s live band (static offsets).

    The oldest key q-block i can see is ``qo + i*block_q - (window-1)``;
    clamped so the whole band [start, start + n_band) stays inside
    [0, nk) — edge bands cover extra blocks that _block_live then skips.
    MUST match the kv index_map exactly (the kernel recomputes the true
    block index from its band position with this same function)."""
    lo = qo + i * block_q - (window - 1) - ko
    return jnp.clip(jnp.floor_divide(lo, block_k), 0, max(nk - n_band, 0))


def _q_band_start(j, *, qo: int, ko: int, window: int, block_q: int,
                  block_k: int, nq: int, n_band: int):
    """First q-block index of kv-block ``j``'s live band (static offsets):
    the oldest query that can see this block is ``ko + j*block_k - qo``
    (causal).  Same clamp/edge contract as :func:`_kv_band_start`."""
    lo = ko + j * block_k - qo
    return jnp.clip(jnp.floor_divide(lo, block_q), 0, max(nq - n_band, 0))


def _band_setup(window, causal, q_offset, kv_offset, *, span_block: int,
                step_block: int, n_total: int, start_fn, **start_kw):
    """(band_start_fn | None, minor grid size): the ONE place the banded
    sliding-window grid is derived, so the kernel's recomputed block
    index and the index_map can never disagree.  ``span_block`` is the
    major dim's block size (its rows define the band's reach),
    ``step_block`` the minor dim's.  Returns (None, n_total) — full grid
    — unless a window is set, masking is causal, offsets are static
    Python ints, and the band is actually narrower than the full axis."""
    if (window is None or not causal or not isinstance(q_offset, int)
            or not isinstance(kv_offset, int)):
        return None, n_total
    n_band = min(n_total, (span_block + window - 2) // step_block + 2)
    if n_band >= n_total:
        return None, n_total
    fn = functools.partial(start_fn, qo=q_offset, ko=kv_offset,
                           window=window, n_band=n_band, **start_kw)
    return fn, n_band


def _banded_minor_map(band_fn, head_group: int = 1):
    """Minor-axis BlockSpec index_map: grid position ``minor`` offset by
    the band start of ``major`` (identity map when not banded).
    ``head_group`` > 1 is GQA: q head ``h`` reads kv head ``h // group``
    (consecutive q heads share a kv head, the jnp.repeat layout)."""
    g = head_group
    if band_fn is None:
        return lambda b, h, major, minor: (b, h // g, minor, 0)
    return lambda b, h, major, minor: (b, h // g, band_fn(major) + minor, 0)


def _valid_mask(qo_ref, ko_ref, i, j, block_q: int, block_k: int,
                kv_len: int, causal: bool, window: Optional[int] = None):
    """[block_q, block_k] score-validity mask: k-padding rows out, (for
    causal) global q position >= global k position, and (for sliding
    ``window``, causal-only) global q position - global k position <
    ``window`` — each q attends to itself and the ``window - 1`` keys
    before it.  Forward and backward kernels MUST mask identically — the
    backward recomputes p against the forward's lse — so all of them call
    this one helper."""
    kv_offset = ko_ref[0]
    k_global = kv_offset + j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = k_global < kv_offset + kv_len
    if causal:
        q_global = qo_ref[0] + i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        valid = jnp.logical_and(valid, q_global >= k_global)
        if window is not None:
            valid = jnp.logical_and(valid, q_global - k_global < window)
    return valid


def _flash_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, o_ref, *rest,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  kv_len: int, residuals: bool,
                  window: Optional[int] = None, band_j0=None):
    if residuals:
        m_out_ref, l_out_ref, m_ref, l_ref, acc_ref = rest
    else:
        m_ref, l_ref, acc_ref = rest
    jb = pl.program_id(3)  # band position when band_j0, else kv block
    nb = pl.num_programs(3)

    @pl.when(jb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    i = pl.program_id(2)
    # Banded grid (static offsets + window): the grid's minor dim spans
    # only the O(window/block_k) live band; recover the true kv-block
    # index with the SAME band-start function the index_map used.
    j = band_j0(i) + jb if band_j0 is not None else jb
    live = _block_live(qo_ref, ko_ref, i, j, block_q, block_k, kv_len,
                       causal, window)
    full = _block_full(qo_ref, ko_ref, i, j, block_q, block_k, kv_len,
                       causal, window)

    def _update(masked):
        q = q_ref[0, 0]  # [block_q, D]
        k = k_ref[0, 0]  # [block_k, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if scale != 1.0:  # statically elided under Config.flash_prescale
            s = s * scale

        if masked:
            s = jnp.where(_valid_mask(qo_ref, ko_ref, i, j, block_q,
                                      block_k, kv_len, causal, window),
                          s, NEG_INF)

        m_prev = jnp.max(m_ref[:], axis=1, keepdims=True)  # [block_q, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Fully-masked-so-far rows have m_new == NEG_INF; exponentiate
        # against 0 there so masked scores give p == 0, not
        # exp(-1e30 + 1e30) == 1.  (A FULL block always yields finite
        # m_new, but the rescale must still guard m_prev rows from
        # earlier fully-masked blocks, so the guard stays in both paths.)
        m_safe = jnp.where(m_new > 0.5 * NEG_INF, m_new, 0.0)
        alpha = jnp.exp(m_prev - m_safe)  # 0 when m_prev is NEG_INF (init)
        p = jnp.exp(s - m_safe)  # masked entries: exp(NEG_INF) == 0
        l_prev = jnp.max(l_ref[:], axis=1, keepdims=True)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    # Full blocks (the interior majority at production shapes) skip the
    # iota/compare/select mask math entirely — see _block_full.
    @pl.when(jnp.logical_and(live, full))
    def _update_full():
        _update(masked=False)

    @pl.when(jnp.logical_and(live, jnp.logical_not(full)))
    def _update_partial():
        _update(masked=True)

    @pl.when(jb == nb - 1)
    def _finalize():
        # Read the running state back from scratch (NOT the _update
        # locals): the final j block can itself be skipped, e.g. the
        # first q block of a causal layout never sees the last k block.
        m_fin = jnp.max(m_ref[:], axis=1, keepdims=True)  # [block_q, 1]
        l_fin = jnp.max(l_ref[:], axis=1, keepdims=True)
        if residuals:
            # Numerator + statistics for a cross-block combiner; rows whose
            # every key was masked carry m == NEG_INF, l == 0, acc == 0.
            o_ref[0, 0] = acc_ref[:].astype(o_ref.dtype)
            m_out_ref[0, 0] = jnp.broadcast_to(m_fin,
                                               (block_q, _STAT_LANES))
            l_out_ref[0, 0] = jnp.broadcast_to(l_fin,
                                               (block_q, _STAT_LANES))
        else:
            # Fully-masked rows (l == 0) read as zeros, matching the
            # parallel variants' convention in parallel/sequence.py.
            denom = jnp.where(l_fin > 0, l_fin, 1.0)
            o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _flash_bwd_dq_kernel(qo_ref, ko_ref, q_ref, do_ref, lse_ref, d_ref,
                         k_ref, v_ref, dq_ref, dq_acc, *, scale: float,
                         causal: bool, block_q: int, block_k: int,
                         kv_len: int, window: Optional[int] = None,
                         band_j0=None):
    """dq = scale * sum_j [p_ij * (dO_i . v_j - D_i)] k_j, p recomputed
    blockwise from lse.  Grid (B, H, nq, nk) — or (B, H, nq, n_band) on
    the banded window path; the dq accumulator carries across the (minor)
    kv dimension."""
    jb = pl.program_id(3)
    nb = pl.num_programs(3)

    @pl.when(jb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    i = pl.program_id(2)
    j = band_j0(i) + jb if band_j0 is not None else jb
    # Fully-masked blocks contribute p == 0 everywhere, so dq is
    # unchanged — skip all three matmuls.
    live = _block_live(qo_ref, ko_ref, i, j, block_q, block_k, kv_len,
                       causal, window)
    full = _block_full(qo_ref, ko_ref, i, j, block_q, block_k, kv_len,
                       causal, window)

    def _update(masked):
        q = q_ref[0, 0]  # [block_q, D]
        do = do_ref[0, 0]
        k = k_ref[0, 0]  # [block_k, D]
        v = v_ref[0, 0]
        lse = jnp.max(lse_ref[0, 0], axis=1, keepdims=True)  # [block_q, 1]
        dvec = jnp.max(d_ref[0, 0], axis=1, keepdims=True)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if scale != 1.0:  # statically elided under Config.flash_prescale
            s = s * scale
        if masked:
            s = jnp.where(_valid_mask(qo_ref, ko_ref, i, j, block_q,
                                      block_k, kv_len, causal, window),
                          s, NEG_INF)
        p = jnp.exp(s - lse)  # masked / fully-masked rows (lse=+1e30): 0

        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, block_k]
        ds = p * (dp - dvec)
        dqk = jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dq_acc[:] = dq_acc[:] + (scale * dqk if scale != 1.0 else dqk)

    @pl.when(jnp.logical_and(live, full))
    def _update_full():
        _update(masked=False)

    @pl.when(jnp.logical_and(live, jnp.logical_not(full)))
    def _update_partial():
        _update(masked=True)

    @pl.when(jb == nb - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(qo_ref, ko_ref, k_ref, v_ref, q_ref, do_ref,
                          lse_ref, d_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                          scale: float, causal: bool, block_q: int,
                          block_k: int, kv_len: int,
                          window: Optional[int] = None, band_i0=None):
    """dk_j = scale * sum_i ds_ij^T q_i;  dv_j = sum_i p_ij^T dO_i.
    Grid (B, H, nk, nq) — or (B, H, nk, n_band) on the banded window
    path: the q dimension is minor so the dk/dv accumulators carry
    across it for one kv block."""
    ib = pl.program_id(3)
    nb = pl.num_programs(3)

    @pl.when(ib == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    j = pl.program_id(2)
    i = band_i0(j) + ib if band_i0 is not None else ib
    # For this kv block, q blocks entirely in its past (causal)
    # contribute p == 0 — skip all four matmuls.  (Padded keys inside a
    # live block are excluded by _valid_mask, not here.)
    live = _block_live(qo_ref, ko_ref, i, j, block_q, block_k, kv_len,
                       causal, window)
    full = _block_full(qo_ref, ko_ref, i, j, block_q, block_k, kv_len,
                       causal, window)

    def _update(masked):
        k = k_ref[0, 0]  # [block_k, D]
        v = v_ref[0, 0]
        q = q_ref[0, 0]  # [block_q, D]
        do = do_ref[0, 0]
        lse = jnp.max(lse_ref[0, 0], axis=1, keepdims=True)  # [block_q, 1]
        dvec = jnp.max(d_ref[0, 0], axis=1, keepdims=True)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if scale != 1.0:  # statically elided under Config.flash_prescale
            s = s * scale
        if masked:
            s = jnp.where(_valid_mask(qo_ref, ko_ref, i, j, block_q,
                                      block_k, kv_len, causal, window),
                          s, NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_k]

        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - dvec)
        dkq = jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] = dk_acc[:] + (scale * dkq if scale != 1.0 else dkq)

    @pl.when(jnp.logical_and(live, full))
    def _update_full():
        _update(masked=False)

    @pl.when(jnp.logical_and(live, jnp.logical_not(full)))
    def _update_partial():
        _update(masked=True)

    @pl.when(ib == nb - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None, q_offset=0, kv_offset=0,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    window: Optional[int] = None,
                    return_residuals: bool = False, interpret=None):
    """Blocked flash attention on one device.

    ``q``: [B, T_q, H, D]; ``k``/``v``: [B, T_kv, H_kv, D] (the bqhd
    layout of parallel/sequence.py).  ``H_kv`` may be a divisor of ``H``
    (grouped-query attention): q head ``h`` attends against kv head
    ``h // (H // H_kv)`` — the ``jnp.repeat`` layout — with the kv blocks
    fetched once per group straight from the ``H_kv``-headed arrays, no
    repeated tensor ever materialized.  Returns [B, T_q, H, D] in ``q``'s
    dtype — or,
    with ``return_residuals=True``, the tuple ``(numerator, m, l)`` with
    ``numerator`` un-normalized (f32, [B, T_q, H, D]) and ``m``/``l`` the
    per-row softmax max/denominator shaped [B, H, T_q] (f32), the
    partial-block contract of ``parallel.sequence._attn_block`` with
    ``NEG_INF`` in place of -inf.

    ``q_offset``/``kv_offset`` are the global positions of ``q[:, 0]`` and
    ``k[:, 0]`` for causal masking (both 0 for plain self-attention); they
    may be traced int32 scalars, so sequence-sharded callers inside
    ``shard_map`` can pass axis-index-derived offsets.  Numerics match
    :func:`parallel.sequence.reference_attention` to dtype tolerance; the
    [T_q, T_kv] score matrix never exists in memory — VMEM residency is
    O(block_q * block_k + block_q * D) per (batch, head).

    ``window`` (causal only) restricts each query to itself plus the
    ``window - 1`` keys before it (Mistral-style sliding-window
    attention); fully-out-of-window k blocks are skipped at block
    granularity, so cost is O(T * window) instead of O(T^2) — on the
    traced-offset ring path whole out-of-window kv shards skip too.
    """
    B, Tq, H, D = q.shape
    Tkv, Hkv = k.shape[1], k.shape[2]
    if k.shape != (B, Tkv, Hkv, D) or v.shape != k.shape:
        raise ValueError(f"shape mismatch: q {q.shape} k {k.shape} "
                         f"v {v.shape}")
    group = _gqa_group(H, Hkv)
    _check_window(window, causal)
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_q, block_k = _resolve_blocks(block_q, block_k,
                                      "flash_block_q", "flash_block_k")
    if not return_residuals and scale != 1.0 and _prescale_enabled():
        # Plain-forward path of Config.flash_prescale: fold the scale
        # into q once here; the kernel's scale==1.0 guard then elides
        # the per-block multiply.  The residual (ring) path is excluded
        # — its callers compose flash_attention_bwd themselves at the
        # original scale.
        q = _prescale_q(q, scale)
        scale = 1.0

    block_q = _clamp_block(block_q, Tq)
    block_k = _clamp_block(block_k, Tkv)
    pad_q = (-Tq) % block_q
    pad_k = (-Tkv) % block_k
    qt = jnp.moveaxis(q, 2, 1)  # [B, H, Tq, D]
    kt = jnp.moveaxis(k, 2, 1)
    vt = jnp.moveaxis(v, 2, 1)
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    Tqp = qt.shape[2]
    nq = Tqp // block_q
    nk = kt.shape[2] // block_k

    if interpret is None:
        interpret = ring._interpret_mode()

    # Banded grid (window + STATIC offsets — the single-device model
    # path): the minor grid dim spans only the live diagonal band, so
    # iteration count and k/v DMA traffic are O(T * window) instead of
    # O(T^2).  Traced offsets (ring shards) keep the full grid and rely
    # on the runtime _block_live skip.
    band_j0, grid_nk = _band_setup(
        window, causal, q_offset, kv_offset, span_block=block_q,
        step_block=block_k, n_total=nk, start_fn=_kv_band_start,
        block_q=block_q, block_k=block_k, nk=nk)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, kv_len=Tkv, residuals=return_residuals,
        window=window, band_j0=band_j0)
    qo = jnp.asarray(q_offset, jnp.int32).reshape(1)
    ko = jnp.asarray(kv_offset, jnp.int32).reshape(1)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    o_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    kv_map = _banded_minor_map(band_j0, group)
    out_shape = [jax.ShapeDtypeStruct(
        qt.shape, jnp.float32 if return_residuals else q.dtype)]
    out_specs = [o_spec]
    if return_residuals:
        stat = pl.BlockSpec((1, 1, block_q, _STAT_LANES),
                            lambda b, h, i, j: (b, h, i, 0))
        out_shape += [jax.ShapeDtypeStruct((B, H, Tqp, _STAT_LANES),
                                           jnp.float32)] * 2
        out_specs += [stat, stat]
    single = not return_residuals
    result = pl.pallas_call(
        kernel,
        out_shape=out_shape[0] if single else tuple(out_shape),
        grid=(B, H, nq, grid_nk),
        in_specs=[
            smem,
            smem,
            o_spec,
            pl.BlockSpec((1, 1, block_k, D), kv_map),
            pl.BlockSpec((1, 1, block_k, D), kv_map),
        ],
        out_specs=out_specs[0] if single else tuple(out_specs),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom
            pltpu.VMEM((block_q, D), jnp.float32),       # output accum
        ],
        interpret=interpret,
        compiler_params=_flash_params(interpret),
    )(qo, ko, qt, kt, vt)
    out = result if single else result[0]
    if pad_q:
        out = out[:, :, :Tq]
    out = jnp.moveaxis(out, 1, 2)
    if not return_residuals:
        return out
    m, l = result[1], result[2]
    return out, m[:, :, :Tq, 0], l[:, :, :Tq, 0]


def lse_from_residuals(m, l):
    """Log-sum-exp per row from the (m, l) residuals; fully-masked rows
    (l == 0) get +1e30 so the backward recompute ``exp(s - lse)`` is 0."""
    return jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-37)), -NEG_INF)


def _stat_lanes(x, Tqp):
    """[B, H, Tq] stats -> [B, H, Tqp, _STAT_LANES] blocks for the bwd
    kernels; padded q rows get lse=+1e30 (=> p == 0, contributing nothing)."""
    pad = Tqp - x.shape[2]
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad)),
                    constant_values=-NEG_INF)
    return jnp.broadcast_to(x[..., None], (*x.shape, _STAT_LANES))


def flash_attention_bwd(q, k, v, do, lse, dvec, *, causal: bool,
                        scale: float, q_offset=0, kv_offset=0,
                        block_q: int = 128, block_k: int = 128,
                        window: Optional[int] = None, interpret=None):
    """Gradients (dq, dk, dv) in f32 for one (q-shard, kv-shard) pair.

    The flash-attention backward: softmax probabilities are recomputed
    blockwise from ``lse`` (never materializing [T_q, T_kv]), with
    ``dvec[b,h,i] = dO_i . O_i`` supplied by the caller (it is a cheap XLA
    rowsum).  Serves both the single-device VJP and each step of the ring
    backward in parallel/sequence.py, where the kv shard (and its offset)
    rotates.
    """
    B, Tq, H, D = q.shape
    Tkv, Hkv = k.shape[1], k.shape[2]
    group = _gqa_group(H, Hkv)
    _check_window(window, causal)
    block_q = _clamp_block(block_q, Tq)
    block_k = _clamp_block(block_k, Tkv)
    pad_q = (-Tq) % block_q
    pad_k = (-Tkv) % block_k
    qt = jnp.moveaxis(q, 2, 1)
    dot_ = jnp.moveaxis(do, 2, 1).astype(jnp.float32)
    kt = jnp.moveaxis(k, 2, 1)
    vt = jnp.moveaxis(v, 2, 1)
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
        dot_ = jnp.pad(dot_, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    Tqp, Tkvp = qt.shape[2], kt.shape[2]
    nq, nk = Tqp // block_q, Tkvp // block_k
    lse_l = _stat_lanes(lse, Tqp)
    # dvec's padding value is irrelevant (padded rows have p == 0, so
    # ds == p * (dp - dvec) == 0); _stat_lanes' +1e30 never produces nan.
    d_l = _stat_lanes(dvec, Tqp)

    if interpret is None:
        interpret = ring._interpret_mode()

    # Banded grids for static offsets + window — see flash_attention.
    band_j0, grid_nk = _band_setup(
        window, causal, q_offset, kv_offset, span_block=block_q,
        step_block=block_k, n_total=nk, start_fn=_kv_band_start,
        block_q=block_q, block_k=block_k, nk=nk)
    band_i0, grid_nq = _band_setup(
        window, causal, q_offset, kv_offset, span_block=block_k,
        step_block=block_q, n_total=nq, start_fn=_q_band_start,
        block_q=block_q, block_k=block_k, nq=nq)

    qo = jnp.asarray(q_offset, jnp.int32).reshape(1)
    ko = jnp.asarray(kv_offset, jnp.int32).reshape(1)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    qb = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    kb = pl.BlockSpec((1, 1, block_k, D), _banded_minor_map(band_j0, group))
    sb = pl.BlockSpec((1, 1, block_q, _STAT_LANES),
                      lambda b, h, i, j: (b, h, i, 0))

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, kv_len=Tkv, window=window, band_j0=band_j0)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct(qt.shape, jnp.float32),
        grid=(B, H, nq, grid_nk),
        in_specs=[smem, smem, qb, qb, sb, sb, kb, kb],
        out_specs=qb,
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
        compiler_params=_flash_params(interpret),
    )(qo, ko, qt, dot_, lse_l, d_l, kt, vt)

    # dkv grid puts the q-block dimension minor; index maps swap i and j
    # relative to the dq call (grid = (B, H, nk, nq)).  GQA: k/v INPUTS
    # are fetched at the group's kv head (h // group), but the kernel
    # emits PER-Q-HEAD dk/dv partials (out at full H) — writing
    # Hkv-headed outs directly would let each group member's finalize
    # overwrite the last (out blocks are written, not accumulated).  The
    # group-sum afterwards is exactly autodiff's transpose of the
    # jnp.repeat head broadcast.
    kv_in_map2 = lambda b, h, j, i: (b, h // group, j, 0)  # noqa: E731
    kb2 = pl.BlockSpec((1, 1, block_k, D), kv_in_map2)
    dout2 = pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, j, i: (b, h, j, 0))
    q_map2 = _banded_minor_map(band_i0)
    qb2 = pl.BlockSpec((1, 1, block_q, D), q_map2)
    sb2 = pl.BlockSpec((1, 1, block_q, _STAT_LANES), q_map2)
    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, kv_len=Tkv, window=window, band_i0=band_i0)
    dkv_shape = jax.ShapeDtypeStruct((B, H, Tkvp, D), jnp.float32)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=(dkv_shape, dkv_shape),
        grid=(B, H, nk, grid_nq),
        in_specs=[smem, smem, kb2, kb2, qb2, qb2, sb2, sb2],
        out_specs=(dout2, dout2),
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=interpret,
        compiler_params=_flash_params(interpret),
    )(qo, ko, kt, vt, qt, dot_, lse_l, d_l)
    if group > 1:
        dk = dk.reshape(B, Hkv, group, Tkvp, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, group, Tkvp, D).sum(axis=2)

    if pad_q:
        dq = dq[:, :, :Tq]
    if pad_k:
        dk = dk[:, :, :Tkv]
        dv = dv[:, :, :Tkv]
    return (jnp.moveaxis(dq, 1, 2), jnp.moveaxis(dk, 1, 2),
            jnp.moveaxis(dv, 1, 2))


def _float0_zero(x):
    import numpy as np

    return np.zeros(jnp.shape(x), jax.dtypes.float0)


@functools.lru_cache(maxsize=None)
def _flash_vjp(causal: bool, scale: float, block_q: int, block_k: int,
               interp_key, window: Optional[int] = None,
               static_offsets: Optional[tuple] = None,
               prescale: bool = False):
    """custom_vjp instance per static config.  ``interp_key`` is the
    resolved interpret setting (hashable: False or InterpretParams).

    ``static_offsets=(qo, ko)`` bakes Python-int offsets into the closure
    instead of passing them as (traced) arguments — required for the
    banded sliding-window grids, whose index maps need static offsets;
    the instance then takes only (q, k, v).

    ``prescale`` (Config.flash_prescale): q is scaled ONCE at the
    boundary (q' = dtype(q * scale)) and the kernels run scale=1 — the
    forward, the saved residual, and the backward's s-recompute all see
    the SAME q', so lse stays consistent by construction; the chain
    rule puts the scale back on dq (dL/dq = scale * dL/dq')."""

    kw = dict(causal=causal, scale=1.0 if prescale else scale,
              block_q=block_q, block_k=block_k,
              window=window, interpret=interp_key)

    def _maybe_prescale(q):
        return _prescale_q(q, scale) if prescale else q

    # ONE implementation of the VJP math, parameterized over how offsets
    # arrive (baked-in static ints vs traced trailing args).  ``q`` here
    # is ALWAYS the (possibly prescaled) kernel-side q; fwd returns it
    # so the residual saves exactly what the backward must recompute
    # against.
    def _fwd_core(q, k, v, qo, ko):
        q = _maybe_prescale(q)
        num, m, l = flash_attention(q, k, v, q_offset=qo, kv_offset=ko,
                                    return_residuals=True, **kw)
        denom = jnp.where(l > 0, l, 1.0)
        o = (num / jnp.moveaxis(denom, 1, 2)[..., None]).astype(q.dtype)
        return o, lse_from_residuals(m, l), q

    def _bwd_core(q, k, v, o, lse, do, qo, ko):
        dvec = jnp.einsum("bqhd,bqhd->bhq", do.astype(jnp.float32),
                          o.astype(jnp.float32))
        dq, dk, dv = flash_attention_bwd(q, k, v, do, lse, dvec,
                                         q_offset=qo, kv_offset=ko, **kw)
        if prescale:
            dq = dq * scale  # chain rule through q' = scale * q
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    if static_offsets is not None:
        qo_s, ko_s = static_offsets

        @jax.custom_vjp
        def fs(q, k, v):
            return flash_attention(_maybe_prescale(q), k, v,
                                   q_offset=qo_s, kv_offset=ko_s, **kw)

        def fwd_s(q, k, v):
            o, lse, q_used = _fwd_core(q, k, v, qo_s, ko_s)
            return o, (q_used, k, v, o, lse)

        def bwd_s(res, do):
            q_used, k, v, o, lse = res
            return _bwd_core(q_used, k, v, o, lse, do, qo_s, ko_s)

        fs.defvjp(fwd_s, bwd_s)
        return fs

    @jax.custom_vjp
    def f(q, k, v, qo, ko):
        return flash_attention(_maybe_prescale(q), k, v, q_offset=qo,
                               kv_offset=ko, **kw)

    def fwd(q, k, v, qo, ko):
        o, lse, q_used = _fwd_core(q, k, v, qo, ko)
        return o, (q_used, k, v, qo, ko, o, lse)

    def bwd(res, do):
        q_used, k, v, qo, ko, o, lse = res
        return (*_bwd_core(q_used, k, v, o, lse, do, qo, ko),
                _float0_zero(qo), _float0_zero(ko))

    f.defvjp(fwd, bwd)
    return f


def flash_attention_grad(q, k, v, *, causal: bool = False,
                         scale: Optional[float] = None, q_offset=0,
                         kv_offset=0, block_q: Optional[int] = None,
                         block_k: Optional[int] = None,
                         window: Optional[int] = None,
                         interpret=None):
    """Differentiable flash attention (custom VJP with Pallas backward
    kernels).  Same forward semantics as :func:`flash_attention`; gradients
    flow to q/k/v (offsets are integer-like, zero-cotangent).  Pallas has
    no autodiff rule, so this wrapper is what training code should call —
    ``TransformerLM(attn_impl="flash")`` routes here.  Block sizes default
    from Config (``flash_block_q``/``flash_block_k``)."""
    D = q.shape[-1]
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_q, block_k = _resolve_blocks(block_q, block_k,
                                      "flash_block_q", "flash_block_k")
    if interpret is None:
        interpret = ring._interpret_mode()
    prescale = scale != 1.0 and _prescale_enabled()
    if (window is not None and isinstance(q_offset, int)
            and isinstance(kv_offset, int)
            and q_offset == 0 and kv_offset == 0):
        # Zero static offsets (the whole-sequence model path) bake into
        # the closure so the banded O(T*window) grids apply to training
        # too — traced offsets would defeat them.  Restricted to (0, 0)
        # to keep the lru-cached VJP instances bounded: distinct nonzero
        # int offsets (e.g. per-chunk prefill) would each mint a cache
        # entry + compile; those callers get the traced path instead.
        f = _flash_vjp(causal, float(scale), block_q, block_k, interpret,
                      window, static_offsets=(0, 0), prescale=prescale)
        return f(q, k, v)
    f = _flash_vjp(causal, float(scale), block_q, block_k, interpret,
                   window, prescale=prescale)
    return f(q, k, v, jnp.asarray(q_offset, jnp.int32),
             jnp.asarray(kv_offset, jnp.int32))

"""torchmpi_tpu — a TPU-native distributed-communication library with the
capabilities of facebookarchive/TorchMPI, rebuilt idiomatically on JAX/XLA.

TorchMPI was a communication library plus two thin integration layers (``nn``
grad sync and an async parameter server), not a trainer (SURVEY.md §1).  This
package keeps that shape:

    import torchmpi_tpu as mpi
    mpi.init()                         # mpi.start()
    mpi.rank(), mpi.size()             # process rank/size
    mpi.allreduce(x)                   # mpi.allreduceTensor
    h = mpi.async_.allreduce(x)        # mpi.async.allreduceTensor
    mpi.sync_handle(h)                 # mpi.syncHandle
    mpi.nn.synchronize_gradients(...)  # torchmpi.nn.synchronizeGradients
    mpi.parameterserver.init(...)      # torchmpi.parameterserver
    mpi.stop()

(``nn`` and ``parameterserver`` are imported lazily below if present; they
land as separate modules in this package.)

Reference citations throughout are reconstructed (the reference mount was
empty during the survey — SURVEY.md §0) and cited at file-path granularity
with confidence tags.
"""

from .utils import jaxcompat as _jaxcompat

# Backfill modern jax names (jax.shard_map / check_vma) onto older jax
# BEFORE any module that uses them is imported — including test modules
# that do `from jax import shard_map` after importing this package.
_jaxcompat.install()

from .config import Config
from .runtime import (
    init,
    stop,
    is_initialized,
    rank,
    size,
    local_rank,
    device_count,
    local_device_count,
    barrier,
    world_mesh,
    current_mesh,
    push_communicator,
    pop_communicator,
    communicator,
    set_config,
    config,
    DCN_AXIS,
    ICI_AXIS,
    WORLD_AXES,
)
from . import collectives
from . import fusion
from . import planner
from . import selector
from . import tuning
from . import parallel
from . import ops
from . import nn
from . import parameterserver
from . import recipes
from .collectives import (
    allreduce,
    broadcast,
    reduce,
    allgather,
    reduce_scatter,
    sendreceive,
    alltoall,
    gather,
    scatter,
    async_,
    async_in_axis,
    sync_handle,
    wait_all,
    AsyncHandle,
)
from .utils.compilegate import (
    CompileBudgetError,
    compile_budget,
    install as _install_compile_gate,
)

# Arm the relay compile-budget gate for EVERY client of this library at
# import time (round-3 postmortem: prose discipline does not survive;
# the rule has to live in the library).  Passive unless the axon relay
# platform dispatches a large cold compile; opt out with
# TORCHMPI_TPU_COMPILE_GATE=0.
_install_compile_gate()

# The static analyzer, observability, fault-layer, elastic-gang, and
# guard subpackages load lazily (PEP 562): with Config.analysis="off" /
# Config.obs="off" / Config.faults="off" / Config.elastic="off" /
# Config.guard="off" — the defaults — `import torchmpi_tpu` never
# imports them, keeping the zero-added-cost claims literal (tests
# assert the modules are absent from sys.modules).  Any access
# (`mpi.analysis`, `mpi.obs`, `mpi.faults`, `mpi.elastic`,
# `mpi.guard`, `from torchmpi_tpu import obs`) imports on first touch.
def __getattr__(name):
    if name in ("analysis", "obs", "faults", "elastic", "guard"):
        # importlib, not ``from . import``: the from-import form does a
        # hasattr() probe on this package first, which would re-enter
        # this very function.
        import importlib

        mod = importlib.import_module(__name__ + "." + name)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# When the analyzer env opt-in is set, arm the findings capture at
# import (not just init()): scripts/lint_collectives.py lints example
# entry points by reading the TORCHMPI_TPU_ANALYSIS_OUT report, and an
# example that never calls init() (single-device baselines) must still
# leave an (empty) report rather than look like a crashed run.  Env
# parsing matches runtime.init's normalization ("1"/"true" == "warn").
import os as _os

from .runtime import _normalize_analysis as _norm_analysis

if _norm_analysis(_os.environ.get("TORCHMPI_TPU_ANALYSIS",
                                  "off")) in ("warn", "error"):
    __getattr__("analysis").arm_runtime_capture()

__version__ = "0.1.0"

__all__ = [
    "Config", "init", "stop", "is_initialized", "rank", "size", "local_rank",
    "device_count", "local_device_count", "barrier", "world_mesh",
    "current_mesh", "push_communicator", "pop_communicator", "communicator",
    "set_config", "config", "DCN_AXIS", "ICI_AXIS", "WORLD_AXES",
    "collectives", "fusion", "planner", "selector", "tuning", "analysis",
    "obs", "faults", "elastic", "guard", "parallel",
    "allreduce",
    "broadcast", "reduce",
    "allgather", "reduce_scatter", "sendreceive", "alltoall", "gather",
    "scatter", "async_", "sync_handle", "AsyncHandle", "compile_budget",
    "CompileBudgetError", "__version__",
]

"""Synchronous and asynchronous collectives on JAX arrays and pytrees.

Rebuild of the reference's collective engine + Lua API surface (SURVEY.md §3
C3/C5/C7/C9, reconstructed — reference mount empty, SURVEY.md §0):
``allreduceTensor / broadcastTensor / reduceTensor / allgatherTensor /
sendreceiveTensor`` plus the ``mpi.async.*`` variants and ``mpi.syncHandle``.

Two usage modes:

1. **In-axis mode** — functions named ``*_in_axis`` are used *inside* user
   ``shard_map``/``jit`` code and take JAX axis names.  This is the TPU-native
   hot path: the collective compiles into the surrounding step (the analog of
   the reference's C functions called from the training loop).

2. **Eager rank-major mode** — functions named like the reference
   (``allreduce(x)``) take an array whose leading axis is the "rank" axis
   (length = device count of the current communicator mesh).  Slice ``i`` is
   rank ``i``'s tensor; the result has the same leading axis holding each
   rank's output buffer.  This mirrors TorchMPI's per-rank tensor semantics
   exactly and is what the correctness tests sweep (SURVEY.md §5).

Async: ``async_.*`` returns a first-class :class:`AsyncHandle` — on the
direct path XLA dispatch is already asynchronous and the handle wraps the
enqueued buffers; on the staged-host path the whole exchange runs on a
background worker (the analog of the reference's collective thread pool),
optionally donating the input's device buffers once staged.  ``sync_handle``
/ ``AsyncHandle.wait`` block; ``wait_all`` batches; ``done`` polls without
blocking and a FAILED computation polls done with its error surfaced.
``async_in_axis.*`` are the trace-time equivalents for code inside
shard_map/jit: dispatch at the call, data dependency deferred to ``wait()``
— the overlap window the latency-hiding scheduler fills (SURVEY.md §4.4).
Ordering of two async collectives touching the same buffer is preserved by
JAX data dependencies on the direct path and by the single FIFO staged
worker on the host path.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from . import fusion, planner, runtime, selector

AxisNames = Union[str, Tuple[str, ...]]

_REDUCERS = {
    "sum": lax.psum,
    "mean": lax.pmean,
    "max": lax.pmax,
    "min": lax.pmin,
}


def _axes_tuple(axis_names: AxisNames) -> Tuple[str, ...]:
    return (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)


# ---------------------------------------------------------------------------
# Stock XLA implementations (the reference's "mpi"/"nccl" analog: SURVEY C3).
# Each takes per-device values + axis names; must be traceable under jit.
# ---------------------------------------------------------------------------


def _xla_allreduce(x, axis_names, *, op="sum"):
    return _REDUCERS[op](x, _axes_tuple(axis_names))


def _chain_broadcast(x, axes, *, root: int, n: int, k: int):
    """Pipelined-chain broadcast: the tensor splits into ``k`` chunks that
    stream down the ring ``root -> root+1 -> ... -> root+n-1``; at round t
    the link (v, v+1) carries chunk ``t - v``, so after the pipeline fills
    every link moves a fresh chunk every round.  Wire time ~ (k+n-2)/k * size
    / link-BW — approaching the 1x lower bound for k >> n, vs ~2x for the
    masked-psum form (a full allreduce for a root-to-all op; VERDICT round 1
    weak item 5).  ``v`` is the virtual (root-relative) rank.
    """
    shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % k
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    chunks = flat.reshape(k, -1)
    r = lax.axis_index(axes)
    v = lax.rem(r - root + n, n)
    perm = [((root + i) % n, (root + i + 1) % n) for i in range(n - 1)]
    out = jnp.where(v == 0, chunks, jnp.zeros_like(chunks))

    # Rolled with fori_loop, not a Python loop (VERDICT r3 weak #6): the
    # neighbor permutation is the same every round — only the chunk
    # index varies with t — so the HLO holds ONE ppermute however large
    # k + n grows (at 256 chips an unrolled chain would inline hundreds
    # of sequential collectives per op).
    def round_t(t, carry):
        out, buf = carry
        src = lax.dynamic_index_in_dim(
            chunks, jnp.minimum(t, k - 1), 0, keepdims=False)
        send = jnp.where(v == 0, src, buf)
        recv = lax.ppermute(send, axes, perm=perm)
        # Device v receives chunk t - v + 1 this round (valid
        # mid-pipeline).
        idx = t - v + 1
        valid = (v >= 1) & (idx >= 0) & (idx < k)
        idx_c = jnp.clip(idx, 0, k - 1)
        cur = lax.dynamic_index_in_dim(out, idx_c, 0, keepdims=False)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(valid, recv, cur), idx_c, 0)
        return out, recv

    out, _ = lax.fori_loop(0, k + n - 2, round_t, (out, chunks[0]))
    flat_out = out.reshape(-1)
    if pad:
        flat_out = flat_out[:flat_out.shape[0] - pad]
    return flat_out.reshape(shape)


def _xla_broadcast(x, axis_names, *, root=0):
    """Broadcast from global rank ``root``.

    Large tensors (>= ``config.chunk_bytes``) use the pipelined-chain
    schedule (~1x tensor size on the wire; see :func:`_chain_broadcast`);
    small ones keep the single-collective masked-psum form, whose one launch
    beats the chain's k+n-2 launches when latency dominates.  The reference
    made the same latency/bandwidth split in its custom collectives via
    chunk-size cutovers (SURVEY.md §4.2).
    """
    axes = _axes_tuple(axis_names)
    n = 1
    for a in axes:
        n *= lax.axis_size(a)
    nbytes = selector.nbytes_of(x)
    chunk_bytes = runtime.effective_config().chunk_bytes
    if n > 1 and nbytes >= chunk_bytes:
        k = max(2, min(4 * n, -(-nbytes // chunk_bytes)))
        return _chain_broadcast(x, axes, root=root, n=n, k=k)
    r = lax.axis_index(axes)
    masked = jnp.where(r == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axes)


def _xla_reduce(x, axis_names, *, root=0, op="sum"):
    axes = _axes_tuple(axis_names)
    s = _REDUCERS[op](x, axes)
    r = lax.axis_index(axes)
    # Non-root ranks keep their input, as the reference's MPI_Reduce left
    # non-root buffers untouched.
    return jnp.where(r == root, s, x)


def _xla_allgather(x, axis_names):
    return lax.all_gather(x, _axes_tuple(axis_names), axis=0, tiled=False)


def _xla_reduce_scatter(x, axis_names, *, op="sum"):
    # ValueError, not assert: an unsupported reduction must fail loudly
    # under ``python -O`` too, instead of silently computing a sum.
    if op != "sum":
        raise ValueError(f"reduce_scatter supports op='sum', got {op!r}")
    return lax.psum_scatter(x, _axes_tuple(axis_names), scatter_dimension=0,
                            tiled=True)


def _xla_sendreceive(x, axis_names, *, src=0, dst=1):
    axes = _axes_tuple(axis_names)
    recv = lax.ppermute(x, axes, perm=[(src, dst)])
    r = lax.axis_index(axes)
    return jnp.where(r == dst, recv, x)


def _xla_alltoall(x, axis_names, *, split_axis=0, concat_axis=0):
    return lax.all_to_all(x, _axes_tuple(axis_names), split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def _chain_gather(x, axes, *, root: int, n: int):
    """Convergecast chain gather: every device forwards its buffer one hop
    toward root each round; after round t root holds the tensor that
    started at virtual rank t+1.  The bottleneck link (into root) carries
    (n-1) per-rank tensors ~= 1x the gathered size — the O(size) wire
    profile of the reference's MPI_Gather — and total traffic is
    n(n-1)/2 tensor-hops, half the ring allgather's n(n-1) (which then
    masks an n-times-larger buffer on every device)."""
    r = lax.axis_index(axes)
    v = lax.rem(r - root + n, n)
    perm = [((root + i + 1) % n, (root + i) % n) for i in range(n - 1)]
    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = lax.dynamic_update_index_in_dim(
        out, jnp.where(v == 0, x, jnp.zeros_like(x)), root, 0)

    # fori_loop, same rationale as _chain_broadcast (weak #6): one
    # ppermute in the HLO regardless of n.
    def round_t(t, carry):
        out, buf = carry
        recv = lax.ppermute(buf, axes, perm=perm)
        g = lax.rem(root + t + 1, n)  # global rank arriving at root now
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(v == 0, recv, jnp.zeros_like(recv)), g, 0)
        return out, recv

    out, _ = lax.fori_loop(0, n - 1, round_t, (out, x))
    return out


def _xla_gather(x, axis_names, *, root=0):
    """MPI_Gather: root's output is the stack ``[group, ...]`` of every
    rank's tensor; non-root outputs are zeros of the same shape (the
    reference left non-root buffers untouched, which SPMD's uniform result
    shapes cannot express — zeros is the defined analog).

    Large tensors (>= ``config.chunk_bytes``) take the convergecast chain
    (O(size) wire, like the reference's MPI_Gather); small ones keep the
    one-launch allgather+mask whose single collective wins when latency
    dominates — the same latency/bandwidth cutover as broadcast."""
    axes = _axes_tuple(axis_names)
    n = 1
    for a in axes:
        n *= lax.axis_size(a)
    if n > 1 and selector.nbytes_of(x) >= \
            runtime.effective_config().chunk_bytes:
        return _chain_gather(x, axes, root=root, n=n)
    g = lax.all_gather(x, axes, axis=0, tiled=False)
    return jnp.where(lax.axis_index(axes) == root, g, jnp.zeros_like(g))


def _chain_scatter(x, axes, *, root: int, n: int):
    """Chain scatter, farthest-destination-first: at round t root injects
    the chunk for virtual rank n-1-t; each device forwards what it
    received last round, and — because injection is farthest-first —
    every device's own chunk is exactly what arrives in the final round.
    The bottleneck link (out of root) carries (n-1)/n of the payload
    once ~= 1x, and no device ever materializes more than one chunk —
    versus broadcast-then-slice, which ships the full n-chunk tensor to
    every device before slicing 1/n of it."""
    chunk = x.shape[0] // n
    chunks = x.reshape((n, chunk) + x.shape[1:])
    r = lax.axis_index(axes)
    v = lax.rem(r - root + n, n)
    perm = [((root + i) % n, (root + i + 1) % n) for i in range(n - 1)]

    # fori_loop, same rationale as _chain_broadcast (weak #6): one
    # ppermute in the HLO regardless of n.
    def round_t(t, buf):
        g = lax.rem(root + (n - 1 - t), n)  # dst injected this round
        src = lax.dynamic_index_in_dim(chunks, g, 0, keepdims=False)
        send = jnp.where(v == 0, src, buf)
        return lax.ppermute(send, axes, perm=perm)

    buf = lax.fori_loop(0, n - 1, round_t, jnp.zeros_like(chunks[0]))
    # Round n-2 delivered every non-root device its own chunk; root keeps
    # its slice of the input.
    own = lax.dynamic_index_in_dim(chunks, jnp.asarray(root), 0,
                                   keepdims=False)
    return jnp.where(v == 0, own, buf)


def _xla_scatter(x, axis_names, *, root=0):
    """MPI_Scatter: ``x`` is rank ``root``'s tensor with leading dim
    divisible by the group size; rank i receives chunk i.  Large tensors
    (>= ``config.chunk_bytes``) take the chain scatter (O(size) wire,
    one chunk of memory per device); small ones keep broadcast+slice,
    whose single masked-psum launch wins when latency dominates."""
    axes = _axes_tuple(axis_names)
    n = 1
    for a in axes:
        n *= lax.axis_size(a)
    if x.shape[0] % n != 0:
        raise ValueError(
            f"scatter needs leading dim divisible by group size: "
            f"{x.shape[0]} % {n}")
    chunk = x.shape[0] // n
    if n > 1 and selector.nbytes_of(x) >= \
            runtime.effective_config().chunk_bytes:
        return _chain_scatter(x, axes, root=root, n=n)
    src = _xla_broadcast(x, axes, root=root)
    return lax.dynamic_slice_in_dim(src, lax.axis_index(axes) * chunk,
                                    chunk, axis=0)


for _op, _fn in [
    ("allreduce", _xla_allreduce),
    ("broadcast", _xla_broadcast),
    ("reduce", _xla_reduce),
    ("allgather", _xla_allgather),
    ("reduce_scatter", _xla_reduce_scatter),
    ("sendreceive", _xla_sendreceive),
    ("alltoall", _xla_alltoall),
    ("gather", _xla_gather),
    ("scatter", _xla_scatter),
]:
    selector.register(_op, "xla", _fn)


# ---------------------------------------------------------------------------
# In-axis public API: selector-routed, usable inside shard_map/jit.
# ---------------------------------------------------------------------------


def _config_backend(op_name: str, cfg) -> Tuple[str, bool]:
    """Resolve the config-level backend for ``op_name``: per-op table
    first (a deliberate choice, carrying explicit/per-call authority),
    then the hierarchical flag, then the config default.  The ONE home
    of this precedence — shared by _pick and the eager "auto" trigger
    so they can never drift apart."""
    if cfg.backend_per_op:
        b = cfg.backend_per_op.get(op_name)
        if b is not None:
            return b, True
    return ("hierarchical" if cfg.hierarchical else cfg.backend), False


def _pick(op_name: str, x, backend: Optional[str], axes: Tuple[str, ...],
          mesh: Optional[Mesh] = None, cfg=None):
    explicit = backend is not None
    if cfg is not None or runtime.is_initialized():
        if cfg is None:
            cfg = runtime.config()
        if backend is None:
            # A per-op table entry bypasses the size cutover like a
            # per-call backend (topology fallback still applies).
            backend, explicit = _config_backend(op_name, cfg)
        custom_min = cfg.custom_min_bytes
    else:
        backend = backend or "xla"
        custom_min = 0
    # Hierarchical staging only helps when the outer axis really spans more
    # than one slice; use the actual mesh extent, not the axis-name count.
    n_dcn = 1
    if len(axes) > 1:
        m = mesh
        if m is None and runtime.is_initialized():
            m = runtime.current_mesh()
        n_dcn = int(m.shape[axes[0]]) if (m is not None
                                          and axes[0] in m.shape) else 2
    return selector.select(
        op_name,
        backend,
        nbytes=selector.nbytes_of(x),
        custom_min_bytes=custom_min,
        n_dcn=n_dcn,
        explicit=explicit,
        dtype=getattr(x, "dtype", None),
        axes=axes,
    )


def _obs_in_axis(op_name: str, x, axes: Tuple[str, ...]) -> None:
    """Telemetry note for one in-axis call (``torchmpi_tpu.obs``).
    Trace-time only — jit replays never re-enter — and one branch per
    call when obs is off (the module is never imported then).  Gates on
    ``effective_config`` like every other trace-time hook (fusion,
    ZeRO, ps): live config when initialized, defaults (off) otherwise."""
    if runtime.effective_config().obs != "off":
        from . import obs

        obs.record_in_axis(op_name, selector.nbytes_of(x), axes)


def _in_axis(op_name: str, x, axes: Tuple[str, ...],
             backend: Optional[str], params: dict):
    """Shared dispatch for the nine in-axis verbs: replay a cached
    :class:`~torchmpi_tpu.planner.CollectivePlan` (one table lookup —
    fusion bucketing, per-bucket/per-leaf backend choice, and obs
    enablement all pre-resolved), or fall back to the legacy per-call
    derivation for unplannable trees / a disabled planner."""
    plan = planner.plan_in_axis(op_name, x, axes, backend, params)
    if plan is not None:
        return plan.replay(x)
    _obs_in_axis(op_name, x, axes)
    if op_name in fusion.ELEMENTWISE_OPS:
        fused = fusion.maybe_fuse(op_name, x, axes, backend=backend,
                                  **params)
        if fused is not None:
            return fused
    elif op_name == "reduce_scatter":
        fused = fusion.maybe_fuse_reduce_scatter(x, axes, backend=backend,
                                                 **params)
        if fused is not None:
            return fused
    return jax.tree.map(lambda v: _pick(op_name, v, backend, axes)(
        v, axes, **params), x)


def allreduce_in_axis(x, axis_names: AxisNames, *, op: str = "sum",
                      backend: Optional[str] = None):
    """Allreduce across mesh axes; for use inside shard_map (hot path).

    Multi-leaf pytrees coalesce into dtype-grouped, size-bucketed flat
    transfers (``config.fuse_max_bytes``; one selector-routed collective
    per bucket, bit-identical results) instead of one launch per leaf —
    see :mod:`torchmpi_tpu.fusion`.  The whole decision (bucketing,
    per-bucket backend, obs) is planned once per tree structure and
    replayed (:mod:`torchmpi_tpu.planner`)."""
    return _in_axis("allreduce", x, _axes_tuple(axis_names), backend,
                    {"op": op})


def broadcast_in_axis(x, axis_names: AxisNames, *, root: int = 0,
                      backend: Optional[str] = None):
    return _in_axis("broadcast", x, _axes_tuple(axis_names), backend,
                    {"root": root})


def reduce_in_axis(x, axis_names: AxisNames, *, root: int = 0, op: str = "sum",
                   backend: Optional[str] = None):
    return _in_axis("reduce", x, _axes_tuple(axis_names), backend,
                    {"root": root, "op": op})


def allgather_in_axis(x, axis_names: AxisNames, *,
                      backend: Optional[str] = None):
    return _in_axis("allgather", x, _axes_tuple(axis_names), backend, {})


def reduce_scatter_in_axis(x, axis_names: AxisNames, *, op: str = "sum",
                           backend: Optional[str] = None):
    return _in_axis("reduce_scatter", x, _axes_tuple(axis_names), backend,
                    {"op": op})


def gather_in_axis(x, axis_names: AxisNames, *, root: int = 0,
                   backend: Optional[str] = None):
    return _in_axis("gather", x, _axes_tuple(axis_names), backend,
                    {"root": root})


def scatter_in_axis(x, axis_names: AxisNames, *, root: int = 0,
                    backend: Optional[str] = None):
    return _in_axis("scatter", x, _axes_tuple(axis_names), backend,
                    {"root": root})


def sendreceive_in_axis(x, axis_names: AxisNames, *, src: int, dst: int,
                        backend: Optional[str] = None):
    return _in_axis("sendreceive", x, _axes_tuple(axis_names), backend,
                    {"src": src, "dst": dst})


def alltoall_in_axis(x, axis_names: AxisNames, *, split_axis: int = 0,
                     concat_axis: int = 0, backend: Optional[str] = None):
    return _in_axis("alltoall", x, _axes_tuple(axis_names), backend,
                    {"split_axis": split_axis, "concat_axis": concat_axis})


# ---------------------------------------------------------------------------
# Eager rank-major mode (TorchMPI tensor semantics; tests + micro-bench).
# The analog of the reference's resource cache (SURVEY §8.4.5) is now
# the CollectivePlan table (torchmpi_tpu/planner.py): one immutable
# plan per (op, avals, mesh, backend, params, config-epoch) holding the
# resolved implementation, compiled executable, cached rank-major
# sharding, and pre-resolved obs/faults enablement.  The module-level
# names below are compatibility aliases into that table.
# ---------------------------------------------------------------------------

_jit_cache: Dict[Any, Any] = planner._table  # alias: THE plan table

# Rank-major NamedSharding per mesh, cached in the planner (building
# one costs Python-side work on EVERY eager dispatch).
_sharding_cache: Dict[Mesh, NamedSharding] = planner._shardings

# Executables of the pre-planner dispatch path (kept for
# `planner.set_enabled(False)` — the --plan-compare bench baseline and
# the bit-identity tests).
_legacy_jit_cache: Dict[Any, Any] = {}


def clear_cache() -> None:
    """Drop every cached collective plan (and legacy executable) — the
    single invalidation point (``planner.invalidate``)."""
    planner.invalidate()
    _legacy_jit_cache.clear()


def _rank_major_sharding(m: Mesh) -> NamedSharding:
    return planner.rank_major_sharding(m)


def _mesh_and_n(mesh: Optional[Mesh]) -> Tuple[Mesh, int]:
    m = mesh if mesh is not None else runtime.current_mesh()
    return m, int(m.devices.size)


_NP_REDUCERS = {
    "sum": lambda a: a.sum(axis=0),
    "mean": lambda a: a.mean(axis=0),
    "max": lambda a: a.max(axis=0),
    "min": lambda a: a.min(axis=0),
}


def _host_staged(op_name: str, xs: np.ndarray, n: int, **params):
    """Host-staged eager collectives (reference:
    ``torchmpi_set_staged_collectives`` — GPU tensors staged through
    pinned host buffers when MPI was not CUDA-aware, SURVEY.md §6.6 and
    §3 C5).  The TPU analog: the rank-major buffers round-trip through
    host memory and the reduction/routing runs on the host CPU; the
    direct path keeps everything on the device fabric.  Semantics match
    the direct implementations op-for-op (tests assert staged == direct
    across the full op sweep)."""
    root = params.get("root", 0)
    if op_name in ("allreduce", "reduce"):
        op = params.get("op", "sum")
        # Match the direct path's dtype promotion: lax.pmean on integer
        # inputs yields float32; every other reduction keeps the input
        # dtype (code review r5 — staged == direct is op-for-op
        # INCLUDING dtype).
        rdt = (np.dtype(np.float32)
               if op == "mean" and not np.issubdtype(xs.dtype, np.inexact)
               else xs.dtype)
        red = _NP_REDUCERS[op](xs).astype(rdt)
        if op_name == "allreduce":
            return np.broadcast_to(red[None], (n,) + red.shape)
        out = xs.astype(rdt).copy()
        out[root] = red
        return out
    if op_name == "broadcast":
        return np.broadcast_to(xs[root][None], xs.shape)
    if op_name == "allgather":
        return np.broadcast_to(xs[None], (n,) + xs.shape)
    if op_name == "gather":
        # Non-root outputs are zeros, matching the direct path's defined
        # analog of MPI's untouched non-root buffers.
        out = np.zeros((n,) + xs.shape, xs.dtype)
        out[root] = xs
        return out
    if op_name == "scatter":
        if xs.shape[1] % n != 0:
            raise ValueError(
                f"scatter needs leading dim divisible by group size: "
                f"{xs.shape[1]} % {n}")
        return np.stack(np.split(xs[root], n, axis=0))
    if op_name == "reduce_scatter":
        # ValueError, not assert: must fail loudly under ``python -O``.
        if params.get("op", "sum") != "sum":
            raise ValueError(
                f"reduce_scatter supports op='sum', "
                f"got {params.get('op')!r}")
        s = xs.sum(axis=0).astype(xs.dtype)
        return np.stack(np.split(s, n, axis=0))
    if op_name == "sendreceive":
        out = xs.copy()
        out[params.get("dst", 1)] = xs[params.get("src", 0)]
        return out
    if op_name == "alltoall":
        sa = params.get("split_axis", 0)
        ca = params.get("concat_axis", 0)
        # pieces[p][j] = rank j's p-th split piece; rank i's output is
        # every rank's piece i, concatenated (tiled all_to_all).
        pieces = np.split(xs, n, axis=sa + 1)
        return np.stack([
            np.concatenate([pieces[i][j] for j in range(n)], axis=ca)
            for i in range(n)])
    raise ValueError(f"host-staged path does not implement {op_name!r}")


def _place_rank_major(x, m: Mesh, sharding: Optional[NamedSharding] = None):
    """Place a host rank-major array onto the mesh, slice i on device i."""
    if sharding is None:
        sharding = _rank_major_sharding(m)
    if jax.process_count() > 1:
        # Multi-host: device_put of a host array onto a global sharding is
        # not allowed; every process passes the identical full rank-major
        # array (SPMD-consistent, TorchMPI's per-rank tensors stacked), and
        # each process contributes its addressable shards.
        flat_devices = list(m.devices.flat)
        shards = []
        for i, d in enumerate(flat_devices):
            if d.process_index == jax.process_index():
                shards.append(jax.device_put(x[i:i + 1], d))
        return jax.make_array_from_single_device_arrays(x.shape, sharding,
                                                        shards)
    return jax.device_put(x, sharding)


def _obs_record_eager(cfg, op_name: str, x, m: Mesh, impl=None) -> None:
    """Telemetry record for one eager dispatch (``torchmpi_tpu.obs``):
    one branch on the off path, recorded BEFORE dispatch so a
    collective the gang never completes is the last flight event.
    ``impl=None`` means the staged-host path.  Per-rank size comes from
    metadata — ``x[0]`` would enqueue a device slice on the hot path
    purely to read shape/dtype."""
    if cfg is None or cfg.obs == "off":
        return
    from . import obs

    backend = "host" if impl is None else selector.name_of(op_name, impl)
    obs.record_eager(op_name,
                     int(np.prod(x.shape[1:])) * x.dtype.itemsize,
                     backend, m, dtype=x.dtype)


def _obs_record_eager_done(cfg, op_name: str, x, m: Mesh,
                           impl=None) -> None:
    """The matching completion edge (flight ring only): recorded AFTER
    the dispatch/exchange returns, so ``obs_tool blame`` can tell
    "launched and stuck" from "launched and done, next never
    launched" (docs/OBSERVABILITY.md)."""
    if cfg is None or cfg.obs == "off":
        return
    from . import obs

    backend = "host" if impl is None else selector.name_of(op_name, impl)
    obs.record_eager_done(op_name,
                          int(np.prod(x.shape[1:])) * x.dtype.itemsize,
                          backend, m)


def _staged_leaf(cfg, op_name: str, x, n: int, params: dict):
    """One leaf's host-staged exchange: the faults-instrumented (sites
    ``host_staged.gather``/``scatter``) or plain host compute, shared by
    the synchronous eager path and the async handle dispatch.  ``x`` may
    be a device array (retries re-stage from it) or, on the async
    worker, an already-staged host master wrapped in
    :class:`_RestageView` so each fault-layer attempt still re-stages a
    fresh writable copy."""
    wire = cfg is not None and cfg.guard in ("wire", "full")
    wd = None
    wd_tok = -1
    if cfg is not None and cfg.watchdog != "off":
        # Live hang detection over the whole exchange
        # (docs/WATCHDOG.md): one string compare when off, the module
        # never imported.  Pending deferred breaks deliver here — the
        # eager boundary — before this dispatch blocks.
        from . import watchdog

        wd = watchdog
        wd.raise_pending()
        wd_tok = wd.begin("host_staged", op=op_name, peer="gang")
    try:
        if (cfg is not None and cfg.faults != "off") or wire:
            from . import faults

            # Injection + retry policy around both staging legs
            # (sites host_staged.gather/scatter — docs/FAULTS.md); the
            # wire guard (docs/GUARD.md) brackets each leg with a sender
            # digest verified at the receiver, riding the same retry
            # loop.  Off is one string compare each, the modules never
            # imported.
            return faults.staged_exchange(op_name, x, n, params,
                                          _host_staged, wire_guard=wire)
        return _host_staged(op_name, np.asarray(x), n, **params)
    finally:
        if wd is not None:
            wd.end(wd_tok)


def _staged_requested(cfg, backend: Optional[str]) -> bool:
    """Whether this dispatch takes the staged-host path (config.staged /
    backend="host"): ONE definition shared by the sync and async eager
    dispatchers, so they can never disagree about which side of the
    device/host boundary a call runs on.  An explicit non-host backend
    argument still forces the direct path, mirroring how per-call
    selector choices overrode the global staged flag."""
    return backend == "host" or (backend is None
                                 and cfg is not None and cfg.staged)


def _check_rank_axis(op_name: str, shape, n: int) -> None:
    """Validate the rank-major leading axis (shared sync/async)."""
    if len(shape) < 1 or shape[0] != n:
        raise ValueError(
            f"{op_name}: leading (rank) axis must have length {n} "
            f"(the current communicator size); got shape {tuple(shape)}"
        )


def _eager_collective(op_name: str, x, *, mesh: Optional[Mesh] = None,
                      backend: Optional[str] = None, **params):
    m, n = _mesh_and_n(mesh)
    x = jnp.asarray(x)
    _check_rank_axis(op_name, x.shape, n)
    if planner.enabled():
        # The steady-state hot path: one plan-table lookup, then the
        # pre-bound replay (impl/executable/sharding/obs/faults all
        # resolved at build — docs/PLANNER.md).
        return planner.plan_for(op_name, x, m, n, backend, params).replay(x)
    return _eager_collective_unplanned(op_name, x, m, n, backend=backend,
                                       **params)


def _eager_collective_unplanned(op_name: str, x, m: Mesh, n: int, *,
                                backend: Optional[str] = None, **params):
    """The pre-planner dispatch path, preserved verbatim: every call
    re-derives staged/auto/selector/obs decisions in sequence and only
    the compiled executable is memoized.  Runs only under
    ``planner.set_enabled(False)`` — the ``--plan-compare`` baseline
    and the planned-vs-unplanned bit-identity tests."""
    # ONE config read per dispatch (it feeds the staged check, the
    # "auto" trigger, and _pick's cutover below — re-reading it three
    # times was measurable Python overhead on the eager hot path).
    cfg = runtime.config() if runtime.is_initialized() else None
    # Staged mode: devices -> host -> compute -> devices, the
    # reference's staged data path.
    if _staged_requested(cfg, backend):
        _obs_record_eager(cfg, op_name, x, m)
        out = _staged_leaf(cfg, op_name, x, n, params)
        placed = _place_rank_major(np.ascontiguousarray(out), m)
        _obs_record_eager_done(cfg, op_name, x, m)
        return placed
    # Online "auto" mode (config default, per-op table, or an explicit
    # backend="auto"): resolve against the persistent tuning plan.  The
    # first eager call of an uncached (op, size bucket, mesh, platform)
    # key measures the registered candidates and persists the winner;
    # every later call — this process or any future one — replays the
    # plan (torchmpi_tpu/tuning/).  A degraded plan resolves to None and
    # the static selector path below applies.
    eff = backend
    if eff is None and cfg is not None:
        eff, _ = _config_backend(op_name, cfg)
    if eff == "auto":
        from . import tuning

        resolved = tuning.resolve_eager(
            op_name, selector.nbytes_of(x[0]), x.dtype, m,
            lambda b: _eager_collective(op_name, x, mesh=m, backend=b,
                                        **params))
        if resolved is not None:
            # A measured decision carries per-call-backend authority
            # (bypasses the size cutover; topology fallback still
            # applies in the selector).
            backend = resolved
    axes = m.axis_names
    # Resolve the implementation *before* the cache lookup: the key must
    # include the resolved impl, or runtime set_config() backend switches
    # would silently reuse a stale executable.
    impl = _pick(op_name, x[0], backend, axes, mesh=m, cfg=cfg)
    _obs_record_eager(cfg, op_name, x, m, impl=impl)
    key = (op_name, m, impl, x.shape, x.dtype.name,
           tuple(sorted(params.items())))
    entry = _legacy_jit_cache.get(key)
    if entry is None:

        def body(xs):
            y = impl(xs[0], axes, **params)
            return y[None]

        lead = P(axes)
        out_spec = lead
        in_spec = lead

        # check_vma=False: the rank-major eager mode states its shardings
        # fully explicitly, and custom (pallas) backends cannot express vma
        # through pallas_call uniformly.
        shmapped = shard_map(body, mesh=m, in_specs=(in_spec,),
                             out_specs=out_spec, check_vma=False)
        # Opt-in static analysis, once per cache entry (Config.analysis;
        # docs/ANALYSIS.md).  Trace-time only — the executable below is
        # what every later call replays, so the steady state pays
        # nothing; with the default "off" this branch never imports the
        # analyzer at all.
        mode = getattr(cfg, "analysis", "off") if cfg is not None else "off"
        if mode in ("warn", "error"):
            from . import analysis

            analysis.check_once(
                f"eager {op_name}", shmapped,
                jax.ShapeDtypeStruct(x.shape, x.dtype), mode=mode)
        entry = (jax.jit(shmapped), _rank_major_sharding(m))
        _legacy_jit_cache[key] = entry
    fn, sharding = entry
    out = fn(_place_rank_major(x, m, sharding))
    _obs_record_eager_done(cfg, op_name, x, m, impl=impl)
    return out


def allreduce(x, *, op: str = "sum", mesh: Optional[Mesh] = None,
              backend: Optional[str] = None):
    """Reference: ``mpi.allreduceTensor``.  ``x[i]`` is rank i's tensor; every
    slice of the result equals the reduction over ranks.  Works on pytrees."""
    return jax.tree.map(
        lambda v: _eager_collective("allreduce", v, mesh=mesh, backend=backend,
                                    op=op), x)


def broadcast(x, *, root: int = 0, mesh: Optional[Mesh] = None,
              backend: Optional[str] = None):
    """Reference: ``mpi.broadcastTensor(root, t)``."""
    return jax.tree.map(
        lambda v: _eager_collective("broadcast", v, mesh=mesh, backend=backend,
                                    root=root), x)


def reduce(x, *, root: int = 0, op: str = "sum", mesh: Optional[Mesh] = None,
           backend: Optional[str] = None):
    """Reference: ``mpi.reduceTensor(root, t)``; non-root slices unchanged."""
    return jax.tree.map(
        lambda v: _eager_collective("reduce", v, mesh=mesh, backend=backend,
                                    root=root, op=op), x)


def allgather(x, *, mesh: Optional[Mesh] = None,
              backend: Optional[str] = None):
    """Reference: ``mpi.allgatherTensor``.  Result slice i is the stack of all
    ranks' tensors: shape ``[n_ranks, n_ranks, ...]``."""
    return jax.tree.map(
        lambda v: _eager_collective("allgather", v, mesh=mesh,
                                    backend=backend), x)


def reduce_scatter(x, *, mesh: Optional[Mesh] = None,
                   backend: Optional[str] = None):
    """Rank i's slice of the result is shard i of the summed tensor (the
    building block of the hierarchical allreduce)."""
    return jax.tree.map(
        lambda v: _eager_collective("reduce_scatter", v, mesh=mesh,
                                    backend=backend), x)


def gather(x, *, root: int = 0, mesh: Optional[Mesh] = None,
           backend: Optional[str] = None):
    """MPI_Gather analog (SURVEY.md §1 cap.2 "gather/allgather variants").
    Slice ``root`` of the result is the stack of all ranks' tensors
    (shape ``[n, n, ...]``); other slices are zeros."""
    return jax.tree.map(
        lambda v: _eager_collective("gather", v, mesh=mesh, backend=backend,
                                    root=root), x)


def scatter(x, *, root: int = 0, mesh: Optional[Mesh] = None,
            backend: Optional[str] = None):
    """MPI_Scatter analog: rank i's result slice is chunk i of rank
    ``root``'s tensor (each rank's tensor is ``[k, ...]`` with ``k``
    divisible by the communicator size; result is ``[n, k/n, ...]``)."""
    return jax.tree.map(
        lambda v: _eager_collective("scatter", v, mesh=mesh, backend=backend,
                                    root=root), x)


def sendreceive(x, *, src: int, dst: int, mesh: Optional[Mesh] = None,
                backend: Optional[str] = None):
    """Reference: ``mpi.sendreceiveTensor``: rank ``dst`` receives rank
    ``src``'s tensor; everyone else keeps theirs."""
    return jax.tree.map(
        lambda v: _eager_collective("sendreceive", v, mesh=mesh,
                                    backend=backend, src=src, dst=dst), x)


def alltoall(x, *, mesh: Optional[Mesh] = None, backend: Optional[str] = None):
    """All-to-all over the rank axis (not in the reference's public Lua API
    but present in MPI; needed later for sequence parallelism)."""
    return jax.tree.map(
        lambda v: _eager_collective("alltoall", v, mesh=mesh, backend=backend,
                                    split_axis=0, concat_axis=0), x)


# ---------------------------------------------------------------------------
# Async facade (reference: mpi.async.* + syncHandle; SURVEY C7 / §4.4).
# ---------------------------------------------------------------------------


def to_local(x):
    """Gather this process's addressable slices of an eager-mode result.

    Multi-host: a rank-major result spans all hosts' devices; each process
    reads back only its local rows (the reference's per-rank output tensor).
    Returns ``[local_ranks, ...]`` stacked in global rank order, with
    ``.indices`` attached via a second return value.
    """
    shards = sorted(x.addressable_shards, key=lambda s: s.index[0].start or 0)
    rows = [np.asarray(s.data) for s in shards]
    idx = [s.index[0].start or 0 for s in shards]
    return np.concatenate(rows, axis=0), idx


class AsyncHandle:
    """First-class handle for an in-flight collective.

    Three flavors, one contract (``wait()`` / ``done`` / ``error``):

    - **direct eager** — XLA has already enqueued the computation;
      ``wait()`` blocks until device buffers are ready and returns them
      (the analog of the reference's future indices from
      ``torchmpi_async_*``).
    - **staged-host** — the devices->host->devices exchange runs on a
      background worker (the reference's collective thread pool);
      the handle owns a future and ``wait()`` joins it, then blocks on
      the placement.  With ``donate=True`` the input's device buffers
      are released as soon as they are staged to host.
    - **trace-time** (the ``async_in_axis`` verbs) — the collective is
      already part of the surrounding jit program; the handle defers
      the *data dependency* until ``wait()``, which is what lets the
      latency-hiding scheduler overlap it with compute issued in
      between (the gradsync overlap schedule builds on the same idea).

    A failed computation is **done** (``done`` -> True) and its error
    is surfaced: ``wait()`` re-raises it and ``error`` exposes it —
    never the old poll-as-never-done masking.
    """

    __slots__ = ("_value", "_future", "_done", "_error", "_op", "_trace")

    def __init__(self, value=None, *, future=None, op: str = "",
                 trace: bool = False):
        self._value = value
        self._future = future
        self._done = trace  # a traced value has no runtime to wait on
        self._error: Optional[BaseException] = None
        self._op = op
        self._trace = trace

    @property
    def op(self) -> str:
        return self._op

    @property
    def error(self) -> Optional[BaseException]:
        """The failure of a completed-with-error handle (else None)."""
        return self._error

    def _resolve_future(self) -> None:
        """Exchange a completed staged future for its placed value (or
        its error)."""
        if self._future is None:
            return
        fut, self._future = self._future, None
        try:
            self._value = fut.result()
        except Exception as e:  # noqa: BLE001 — carried to wait()/done
            self._error = e

    def wait(self, timeout_s: Optional[float] = None):
        """Block until the collective completes; return its result.

        Re-raises the underlying error if the computation failed — on
        every call, so a handle waited twice fails twice rather than
        handing out half-initialized buffers.

        ``timeout_s`` bounds the block: on expiry a typed
        :class:`~torchmpi_tpu.faults.policy.PeerTimeoutError` (carrying
        the obs flight-recorder tail) raises instead of waiting
        forever — the computation itself is NOT cancelled, the caller
        is expected to checkpoint-restore or die, which is the point.
        ``None`` (the default) blocks unbounded and never imports the
        fault layer.  With ``Config.watchdog`` armed the wait is also
        a cooperative break point: a stall the watchdog flags raises
        :class:`~torchmpi_tpu.watchdog.CollectiveHangError` in place
        (docs/WATCHDOG.md)."""
        if self._done:
            if self._error is not None:
                raise self._error
            return self._value
        t0 = time.monotonic()
        wd = None
        if runtime.effective_config().watchdog != "off":
            from . import watchdog

            wd = watchdog
        if timeout_s is None and wd is None:
            # The unbounded fast path: one blocking readiness call.
            self._resolve_future()
            if self._error is None:
                try:
                    jax.block_until_ready(self._value)
                except Exception as e:  # noqa: BLE001 — surfaced below
                    self._error = e
            self._done = True
            _obs_async("wait", self._op, time.monotonic() - t0)
            if self._error is not None:
                raise self._error
            return self._value
        # Bounded / watchdog-armed path: poll readiness so the wait
        # stays interruptible (block_until_ready cannot be unwound).
        tok = wd.begin("async.wait", op=self._op) if wd is not None else -1
        try:
            while not self.done:
                if wd is not None:
                    wd.check_break(tok)
                elapsed = time.monotonic() - t0
                if timeout_s is not None and elapsed >= timeout_s:
                    from .faults.policy import (PeerTimeoutError,
                                                flight_tail)

                    raise PeerTimeoutError(
                        f"async.wait({self._op})", elapsed_s=elapsed,
                        deadline_s=float(timeout_s),
                        flight_tail=flight_tail())
                # Coarsen the poll as the wait ages: sub-ms latency for
                # results that are nearly ready, ~20ms granularity for
                # long waits (the watchdog deadline dwarfs it).
                time.sleep(0.0005 if elapsed < 0.01
                           else (0.002 if elapsed < 0.1 else 0.02))
        finally:
            if wd is not None:
                wd.end(tok)
        _obs_async("wait", self._op, time.monotonic() - t0)
        if self._error is not None:
            raise self._error
        return self._value

    @property
    def done(self) -> bool:
        """Non-blocking poll.  True also when the computation FAILED —
        the error then raises from ``wait()`` (and shows on ``error``);
        only a genuinely still-in-flight computation polls False."""
        if self._done:
            return True
        if self._future is not None:
            if not self._future.done():
                return False
            self._resolve_future()
        if self._error is None:
            try:
                ready = all(
                    leaf.is_ready() if hasattr(leaf, "is_ready") else True
                    for leaf in jax.tree.leaves(self._value)
                )
            except Exception as e:  # noqa: BLE001 — a poll error IS
                # completion: the async computation failed.  The old
                # blanket ``ready = False`` here made failed handles
                # poll as never-done forever.
                self._error = e
                ready = True
            if not ready:
                return False
        self._done = True
        return True


def sync_handle(handle: AsyncHandle):
    """Reference: ``mpi.syncHandle(h)``."""
    return handle.wait()


def wait_all(handles, timeout_s: Optional[float] = None):
    """Batched ``wait()``: block until EVERY handle completes, then
    return their results **in input order** (completion order does not
    reorder anything).  One ``jax.block_until_ready`` spans all device
    values, so a mixed batch synchronizes in a single readiness sweep
    instead of one blocking call per handle.  If any handle failed, the
    first (in input order) error re-raises — after all handles have
    been driven to completion, so no work is silently left in flight.

    ``timeout_s`` is ONE deadline threaded across the whole batch (not
    per handle): each successive wait gets whatever budget the ones
    before it left, so a wedged batch surfaces a typed
    ``PeerTimeoutError`` within ``timeout_s`` total instead of N times
    it.  On a timeout the remaining handles are left in flight — the
    caller is recovering, not harvesting.  With ``Config.watchdog``
    armed (and no timeout) the per-handle waits become cooperative
    break points (docs/WATCHDOG.md) but keep this function's
    completion contract: every handle is still driven to completion
    before the first (input-order) error re-raises — merely arming
    monitoring must not change error semantics.
    """
    hs = list(handles)
    if timeout_s is not None or \
            runtime.effective_config().watchdog != "off":
        t0 = time.monotonic()
        first_err: Optional[BaseException] = None
        for h in hs:
            left = (None if timeout_s is None
                    else max(0.0, float(timeout_s)
                             - (time.monotonic() - t0)))
            try:
                h.wait(timeout_s=left)
            except Exception as e:  # noqa: BLE001 — re-raised below;
                # deliberately NOT BaseException: a KeyboardInterrupt
                # mid-batch must abort NOW, not after blocking on the
                # remaining (possibly wedged) handles.
                if timeout_s is not None:
                    # Bounded batch: abort — the remainder is left in
                    # flight by documented contract (the caller is
                    # recovering, not harvesting).
                    raise
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return [h._value for h in hs]
    t0 = time.monotonic()
    pending = []
    for h in hs:
        if not h._done:
            h._resolve_future()
            if h._error is None:
                pending.append(h)
    try:
        jax.block_until_ready([h._value for h in pending])
    except Exception:  # noqa: BLE001 — attribute per handle below
        # One of the batch failed; fall back to per-handle blocking so
        # the error lands on the handle that owns it.
        for h in pending:
            try:
                jax.block_until_ready(h._value)
            except Exception as e:  # noqa: BLE001
                h._error = e
    dt = time.monotonic() - t0
    waited = False
    for h in hs:
        if not h._done:
            h._done = True
            waited = True
            # Counter + flight event per handle; the blocked time is
            # recorded ONCE below — attributing the whole batch elapsed
            # to every handle would inflate the histogram sum N-fold.
            _obs_async("wait", h._op)
    if waited:
        _obs_async("wait", "wait_all", dt)
    for h in hs:
        if h._error is not None:
            raise h._error
    return [h._value for h in hs]


def _obs_async(event: str, op: str, wait_s: Optional[float] = None,
               x=None) -> None:
    """Handle-lifecycle telemetry (``tm_async_wait_seconds`` + flight
    events) — one string compare when obs is off, module never
    imported (the ``torchmpi_tpu.obs`` discipline).  ``x`` is the raw
    payload; its nbytes walk runs only AFTER the off-gate, so the off
    path never pays a pytree traversal."""
    if runtime.effective_config().obs == "off":
        return
    from . import obs

    obs.record_async(event, op, wait_s=wait_s,
                     nbytes=selector.nbytes_of(x) if x is not None else 0)


# One staged-dispatch worker on purpose: the reference's collective
# thread pool sequenced collectives per communicator, and FIFO
# completion is what makes two async staged collectives on the same
# logical buffer well-ordered without user-side fences.
_staged_pool = None
_staged_pool_lock = threading.Lock()


def _staged_executor():
    global _staged_pool
    if _staged_pool is None:
        with _staged_pool_lock:
            if _staged_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                _staged_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="tm-async-staged")
    return _staged_pool


class _RestageView:
    """Host-staged master buffer presented to the fault layer with the
    device-buffer re-stage contract: each ``np.asarray()`` (one per
    attempt in ``faults.staged_exchange``) returns a FRESH writable
    copy, so an injected corrupt flips real bits in that attempt's
    staging copy while the retry re-stages bit-identical from the
    untouched master — exactly how retries re-stage from real device
    buffers on the synchronous path."""

    __slots__ = ("_master",)

    def __init__(self, master: np.ndarray) -> None:
        self._master = master

    def __array__(self, dtype=None):
        return np.array(self._master, dtype=dtype, copy=True)


def _staged_async_work(op_name: str, leaves, treedef, n: int, m: Mesh,
                       params: dict, cfg, donate: bool):
    """Worker-side staged exchange for one async handle: stage each
    leaf to host (releasing the device buffer immediately when donated),
    run the host compute (faults-instrumented when armed), and place
    the results back rank-major.  Runs on the single staged worker, so
    handles complete in dispatch order."""
    outs = []
    sharding = _rank_major_sharding(m)
    faults_on = cfg is not None and (cfg.faults != "off"
                                     or cfg.guard in ("wire", "full"))
    for v in leaves:
        _obs_record_eager(cfg, op_name, v, m)
        if donate and isinstance(v, jax.Array):
            # np.asarray of a CPU jax array can alias the device
            # buffer; the donated buffer is deleted below, so the
            # staged copy must own its memory.
            hx = np.array(v, copy=True)
            v.delete()
        else:
            hx = np.asarray(v)
        if faults_on:
            # Give the fault layer the device-buffer contract its
            # retries assume: every np.asarray() re-stage yields a
            # FRESH writable attempt copy, so corrupt-then-heal flips
            # real bits in the attempt's staging copy and the retry
            # still re-stages clean from the untouched master.
            hx = _RestageView(hx)
        out = _staged_leaf(cfg, op_name, hx, n, params)
        outs.append(_place_rank_major(np.ascontiguousarray(out), m,
                                      sharding))
        _obs_record_eager_done(cfg, op_name, v, m)
    return jax.tree.unflatten(treedef, outs)


def _async_eager(op_name: str, x, *, mesh: Optional[Mesh] = None,
                 backend: Optional[str] = None, donate: bool = False,
                 **params) -> AsyncHandle:
    """Dispatch an eager collective and return an in-flight handle.

    Direct path: XLA dispatch is already asynchronous — the handle
    wraps the enqueued values.  Staged-host path: the whole exchange
    (readback, host compute, placement) moves to the staged worker so
    the caller never blocks; ``donate=True`` releases each input leaf's
    device buffers the moment it is staged (the ``donate_argnums``
    analog for a path that leaves the XLA program — the buffer is
    consumed by the transfer exactly as a donated jit argument is).
    """
    m, n = _mesh_and_n(mesh)
    cfg = runtime.config() if runtime.is_initialized() else None
    staged = _staged_requested(cfg, backend)
    if not staged:
        value = jax.tree.map(
            lambda v: _eager_collective(op_name, v, mesh=m,
                                        backend=backend, **params), x)
        h = AsyncHandle(value, op=op_name)
        _obs_async("create", op_name, x=x)
        return h
    leaves, treedef = jax.tree.flatten(jax.tree.map(jnp.asarray, x))
    for v in leaves:
        _check_rank_axis(op_name, v.shape, n)
    fut = _staged_executor().submit(
        _staged_async_work, op_name, leaves, treedef, n, m, dict(params),
        cfg, donate)
    h = AsyncHandle(future=fut, op=op_name)
    _obs_async("create", op_name, x=x)
    return h


class _AsyncNamespace:
    """``collectives.async_.allreduce(x)`` -> AsyncHandle (reference:
    ``mpi.async.allreduceTensor``).  Each verb dispatches WITHOUT
    synchronizing — the staged-host path runs on a background worker —
    and accepts ``donate=True`` to release the input's device buffers
    once staged (staged path only; the direct path's buffers belong to
    XLA's ordinary lifetime)."""

    @staticmethod
    def allreduce(x, **kw) -> AsyncHandle:
        return _async_eager("allreduce", x,
                            **{"op": kw.pop("op", "sum"), **kw})

    @staticmethod
    def broadcast(x, **kw) -> AsyncHandle:
        return _async_eager("broadcast", x,
                            **{"root": kw.pop("root", 0), **kw})

    @staticmethod
    def reduce(x, **kw) -> AsyncHandle:
        return _async_eager("reduce", x, **{"root": kw.pop("root", 0),
                                            "op": kw.pop("op", "sum"), **kw})

    @staticmethod
    def allgather(x, **kw) -> AsyncHandle:
        return _async_eager("allgather", x, **kw)

    @staticmethod
    def reduce_scatter(x, **kw) -> AsyncHandle:
        return _async_eager("reduce_scatter", x, **kw)

    @staticmethod
    def gather(x, **kw) -> AsyncHandle:
        return _async_eager("gather", x, **{"root": kw.pop("root", 0), **kw})

    @staticmethod
    def scatter(x, **kw) -> AsyncHandle:
        return _async_eager("scatter", x, **{"root": kw.pop("root", 0), **kw})

    @staticmethod
    def sendreceive(x, *, src: int, dst: int, **kw) -> AsyncHandle:
        return _async_eager("sendreceive", x, src=src, dst=dst, **kw)

    @staticmethod
    def alltoall(x, **kw) -> AsyncHandle:
        return _async_eager("alltoall", x, split_axis=0, concat_axis=0,
                            **kw)


async_ = _AsyncNamespace()


class _AsyncInAxisNamespace:
    """Handle-returning variants of the nine ``*_in_axis`` verbs, for
    use INSIDE shard_map/jit: the collective is issued (traced) at the
    call — riding the same fusion/selector/tuning-plan routing as the
    synchronous verbs — and the handle defers the *data dependency* to
    ``wait()``/``wait_all``.  Everything the program computes between
    dispatch and wait is overlap the latency-hiding scheduler can
    exploit (the reference's ``mpi.async.*`` inside the training loop;
    the gradsync overlap schedule automates the same pattern per
    gradient bucket)."""

    @staticmethod
    def allreduce(x, axis_names: AxisNames, **kw) -> AsyncHandle:
        return AsyncHandle(allreduce_in_axis(x, axis_names, **kw),
                           op="allreduce", trace=True)

    @staticmethod
    def broadcast(x, axis_names: AxisNames, **kw) -> AsyncHandle:
        return AsyncHandle(broadcast_in_axis(x, axis_names, **kw),
                           op="broadcast", trace=True)

    @staticmethod
    def reduce(x, axis_names: AxisNames, **kw) -> AsyncHandle:
        return AsyncHandle(reduce_in_axis(x, axis_names, **kw),
                           op="reduce", trace=True)

    @staticmethod
    def allgather(x, axis_names: AxisNames, **kw) -> AsyncHandle:
        return AsyncHandle(allgather_in_axis(x, axis_names, **kw),
                           op="allgather", trace=True)

    @staticmethod
    def reduce_scatter(x, axis_names: AxisNames, **kw) -> AsyncHandle:
        return AsyncHandle(reduce_scatter_in_axis(x, axis_names, **kw),
                           op="reduce_scatter", trace=True)

    @staticmethod
    def gather(x, axis_names: AxisNames, **kw) -> AsyncHandle:
        return AsyncHandle(gather_in_axis(x, axis_names, **kw),
                           op="gather", trace=True)

    @staticmethod
    def scatter(x, axis_names: AxisNames, **kw) -> AsyncHandle:
        return AsyncHandle(scatter_in_axis(x, axis_names, **kw),
                           op="scatter", trace=True)

    @staticmethod
    def sendreceive(x, axis_names: AxisNames, *, src: int, dst: int,
                    **kw) -> AsyncHandle:
        return AsyncHandle(
            sendreceive_in_axis(x, axis_names, src=src, dst=dst, **kw),
            op="sendreceive", trace=True)

    @staticmethod
    def alltoall(x, axis_names: AxisNames, **kw) -> AsyncHandle:
        return AsyncHandle(alltoall_in_axis(x, axis_names, **kw),
                           op="alltoall", trace=True)


async_in_axis = _AsyncInAxisNamespace()

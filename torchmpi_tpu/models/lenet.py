"""LeNet-5-style convnet for MNIST.

The reference's MNIST examples trained a small convnet of this family
(``examples/mnist*.lua``, SURVEY.md §3 C15 [HIGH] — reconstructed, reference
mount empty).  Shapes are NHWC and channel counts padded toward TPU-friendly
multiples where it is free to do so.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LeNet(nn.Module):
    """conv(32) -> pool -> conv(64) -> pool -> dense(256) -> dense(classes)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):  # x: [B, 28, 28, 1]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x

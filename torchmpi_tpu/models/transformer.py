"""Decoder-only Transformer LM with pluggable sequence-parallel attention.

Not in the reference (pre-transformer library — SURVEY.md §6.7); this is the
long-context model family the TPU rebuild adds, wired to the
sequence-parallel attention strategies in ``parallel/sequence.py``:

- ``attn_impl="local"``   — ordinary full attention (single device / no SP)
- ``attn_impl="flash"``   — Pallas blocked flash attention (ops/flash.py):
  same math as local, [T, T] scores never materialize
- ``attn_impl="ring"``    — blockwise ring attention over ``seq_axis``
- ``attn_impl="ring_flash"`` — ring attention whose per-step local blocks
  run the Pallas flash kernel (long local shards without [T, T] blocks)
- ``attn_impl="ulysses"`` — all-to-all head-scatter attention over ``seq_axis``
- ``attn_impl="ulysses_flash"`` — ulysses with Pallas flash local blocks

With ``seq_axis`` set, the model is meant to run inside ``shard_map`` with
the sequence dimension sharded over that mesh axis; everything except
attention is position-local, so only the attention call communicates.
bfloat16-friendly: set ``dtype=jnp.bfloat16`` for MXU-width matmuls with
float32 parameters and softmax statistics.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ..parallel import expert as eplib
from ..parallel import sequence as seqlib
from .generate import clamp_slot_positions

AxisNames = Union[str, Tuple[str, ...]]


def apply_rope(x, pos, *, base: float = 10000.0):
    """Rotary position embedding (RoPE): rotate feature pairs of ``x``
    ([B, T, H, D], D even) by angles ``pos[t] * base**(-2i/D)``.  Applied
    to q and k before attention — relative positions then live in the
    dot products, so no learned position table exists and decode just
    rotates each new token by its absolute position (``pos`` may be
    traced: cache index, ring-shard offset).  ``pos`` is [T] (one
    position per timestep, shared across the batch) or [B, T] (per-ROW
    positions — the slot-indexed continuous-batching decode, where every
    cache row sits at its own depth)."""
    D = x.shape[-1]
    if D % 2:
        raise ValueError(f"rope requires an even head_dim, got {D}")
    half = D // 2
    inv = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    # [T, half] or [B, T, half]; the head axis is inserted below and the
    # leading batch axis (when absent) broadcasts — bitwise identical to
    # the historical [1, T, 1, half] layout for 1-D pos.
    ang = pos.astype(jnp.float32)[..., None] * inv
    cos = jnp.cos(ang)[..., None, :]  # [(B,) T, 1, half]
    sin = jnp.sin(ang)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


class SPAttention(nn.Module):
    num_heads: int
    head_dim: int
    attn_impl: str = "local"
    seq_axis: Optional[AxisNames] = None
    dtype: jnp.dtype = jnp.float32
    decode: bool = False
    max_len: int = 0
    # Sliding-window attention (Mistral-style): each query sees itself
    # plus the window-1 tokens before it.  Supported by every impl:
    # local/flash (banded O(T*window) kernel grids), ring/ring_flash
    # (global-position band; the flash blocks skip fully-out-of-window
    # work at runtime — the dense ring masks but still pays its einsum,
    # and all n rotations run either way), ulysses/ulysses_flash (banded
    # grids on each head shard), and decode (the cache mask applies the
    # same band).
    window: Optional[int] = None
    # Grouped-query attention: fewer kv heads than q heads (None = MHA).
    # Each kv head serves num_heads/num_kv_heads consecutive q heads;
    # the decode KV cache stores only num_kv_heads — the serving-memory
    # win GQA exists for.  Supported by the "local"/"flash" impls for
    # both training and decode; sequence-parallel impls reject it.
    num_kv_heads: Optional[int] = None
    # Rotary position embeddings: rotate q/k by absolute positions
    # (pos_offset + local index; decode uses the cache index).  The
    # caller (TransformerLM(pos_emb="rope")) then adds no position table.
    rope: bool = False

    @nn.compact
    def __call__(self, x, pos_offset=0):  # x: [B, T_local, E]
        B, T, E = x.shape
        H, D = self.num_heads, self.head_dim
        Hkv = self.num_kv_heads if self.num_kv_heads is not None else H
        if Hkv != H:
            from ..ops.flash import _gqa_group

            _gqa_group(H, Hkv)  # validates divisibility
            if self.attn_impl not in ("local", "flash"):
                raise ValueError(
                    f"num_kv_heads= supports attn_impl='local'/'flash' "
                    f"(got {self.attn_impl!r})")
            q = nn.DenseGeneral((H, D), axis=-1, dtype=self.dtype,
                                name="q")(x).astype(jnp.float32)
            kv = nn.DenseGeneral((2, Hkv, D), axis=-1, dtype=self.dtype,
                                 name="kv")(x)
            k = kv[:, :, 0].astype(jnp.float32)
            v = kv[:, :, 1].astype(jnp.float32)
        else:
            qkv = nn.DenseGeneral((3, H, D), axis=-1, dtype=self.dtype,
                                  name="qkv")(x)
            q, k, v = (qkv[:, :, 0].astype(jnp.float32),
                       qkv[:, :, 1].astype(jnp.float32),
                       qkv[:, :, 2].astype(jnp.float32))
        if self.rope and not self.decode:
            rpos = pos_offset + jnp.arange(T)
            q = apply_rope(q, rpos)
            k = apply_rope(k, rpos)
        if self.decode:
            # Autoregressive KV-cache step: x is the NEW token(s) ([B, 1]
            # in the steady state); keys/values append into this layer's
            # [B, max_len] cache and q attends over the filled prefix.
            # NOT a ring buffer: the caller must keep total decoded length
            # <= max_len (generate() pre-checks; past it,
            # dynamic_update_slice clamps and outputs silently corrupt).
            #
            # Two cache layouts:
            # - "local": single-device, full [B, max_len, H, D] cache.
            # - "ulysses"/"ulysses_flash" with seq_axis (inside shard_map
            #   — the generate_parallel path): HEAD-SHARDED cache — each
            #   device caches H/n heads over the full sequence and
            #   computes attention for them, outputs all_gather back
            #   along the head dim.  The Ulysses decode analog: KV-cache
            #   memory per device is 1/n of the dense layout, the
            #   constraint that actually binds long-context serving.
            # Ring impls have no decode path (their sequence-sharded
            # cache cannot serve one new global token a step).
            ulysses = (self.attn_impl in ("ulysses", "ulysses_flash")
                       and self.seq_axis is not None)
            # "flash" is accepted as an alias of "local" here: decode
            # attends against the cache with the einsum below either
            # way (the train-time kernel never runs in decode), so a
            # flash-trained model serves without rebinding attn_impl.
            if self.attn_impl not in ("local", "flash") and not ulysses:
                raise ValueError(
                    f"decode=True supports attn_impl='local'/'flash' (or "
                    f"'ulysses' under generate_parallel), got "
                    f"{self.attn_impl!r}")
            if self.max_len <= 0:
                raise ValueError("decode=True needs max_len > 0")
            h_cache = Hkv  # GQA: the cache stores only the kv heads
            if ulysses:
                # (GQA cannot reach here: Hkv != H already restricted
                # attn_impl to local/flash above.)
                n_sp = lax.axis_size(self.seq_axis)
                if H % n_sp != 0:
                    raise ValueError(
                        f"ulysses decode needs num_heads {H} divisible "
                        f"by axis size {n_sp}")
                h_cache = H // n_sp
                h0 = lax.axis_index(self.seq_axis) * h_cache
                q = lax.dynamic_slice_in_dim(q, h0, h_cache, 2)
                k = lax.dynamic_slice_in_dim(k, h0, h_cache, 2)
                v = lax.dynamic_slice_in_dim(v, h0, h_cache, 2)
            # Slot-indexed decode (the continuous-batching serving path,
            # torchmpi_tpu/serving/): a 1-D ``pos_offset`` gives every
            # batch row its OWN cache position, so one [S, 1] step can
            # advance S in-flight requests sitting at different depths.
            # The internal ``idx`` counter is neither read nor advanced
            # — the slot engine owns per-row positions.
            po = jnp.asarray(pos_offset)
            per_row = po.ndim == 1
            ck = self.variable("cache", "k", jnp.zeros,
                               (B, self.max_len, h_cache, D), jnp.float32)
            cv = self.variable("cache", "v", jnp.zeros,
                               (B, self.max_len, h_cache, D), jnp.float32)
            idx = self.variable("cache", "idx",
                                lambda: jnp.zeros((), jnp.int32))
            # Write indices route through THE clamp chokepoint
            # (generate.clamp_slot_positions): identity for the valid
            # range the callers guarantee, but it makes the cache writes
            # below statically certifiable (analysis rules S1/S2) —
            # without it an out-of-range index would CLAMP inside
            # dynamic_update_slice and corrupt the last rows silently.
            start = clamp_slot_positions(idx.value, self.max_len, T)
            starts = (clamp_slot_positions(po.astype(jnp.int32),
                                           self.max_len, T)
                      if per_row else None)  # [B]
            if self.rope:
                # Rotate by absolute cache positions, THEN cache: the
                # cache holds rotated keys, so old entries never need
                # re-rotation as decoding advances.
                rpos = (starts[:, None] + jnp.arange(T) if per_row
                        else start + jnp.arange(T))
                q = apply_rope(q, rpos)
                k = apply_rope(k, rpos)
            if per_row:
                row_upd = jax.vmap(
                    lambda c, u, s: lax.dynamic_update_slice(c, u,
                                                             (s, 0, 0)))
                ck.value = row_upd(ck.value, k, starts)
                cv.value = row_upd(cv.value, v, starts)
            else:
                ck.value = lax.dynamic_update_slice(ck.value, k,
                                                    (0, start, 0, 0))
                cv.value = lax.dynamic_update_slice(cv.value, v,
                                                    (0, start, 0, 0))
                idx.value = start + T
            if T > 1 and not per_row:
                # Prefill block (generate's one full-prompt pass onto a
                # FRESH cache): causal attention within the block —
                # O(T^2), not O(T * max_len) against the mostly-empty
                # cache (at max_len 8k and Tp 256 that's 32x wasted score
                # FLOPs/memory).  Assumes start == 0, which is the only
                # way the scalar-offset serving path produces T > 1;
                # chunked prefill with history would need the
                # cache-prefix form.  Per-row T > 1 (the speculative
                # verify step: [S, K+1] tokens at per-slot depths) takes
                # the cache-masked branch below instead — its k/v were
                # just written at rows' own offsets, and the per-row
                # causal mask bounds each query at its own depth.
                o = seqlib.reference_attention(q, k, v, causal=True,
                                               window=self.window)
            else:
                # Steady-state single-token step: query the filled cache.
                # Causal mask over the cache: query t attends to cache
                # positions <= start + t.  Per-row (slot) decode masks
                # each row at its own depth — stale cache beyond a row's
                # filled prefix is -inf'd out, which is what makes slot
                # REUSE bit-identical to a fresh cache without zeroing.
                kv_pos = jnp.arange(self.max_len)
                if per_row:
                    q_pos = starts[:, None] + jnp.arange(T)  # [B, T]
                    mask = kv_pos[None, None, :] <= q_pos[:, :, None]
                    if self.window is not None:
                        mask &= (kv_pos[None, None, :]
                                 > q_pos[:, :, None] - self.window)
                    m_gqa, m_mha = mask[:, None, None], mask[:, None]
                else:
                    q_pos = start + jnp.arange(T)
                    mask = kv_pos[None, :] <= q_pos[:, None]  # [T, max_len]
                    if self.window is not None:
                        # Sliding window over the cache: same band the
                        # training mask applied, so decode logits match
                        # the trained distribution past the window.  (The
                        # cache still stores max_len entries; a rolling
                        # buffer is a memory optimization, not a
                        # semantics change.)
                        mask &= kv_pos[None, :] > q_pos[:, None] - self.window
                    m_gqa, m_mha = mask[None, None, None], mask[None, None]
                if h_cache != q.shape[2]:
                    # GQA (q has more heads than the cache — under
                    # ulysses decode q was head-sliced to h_cache too,
                    # so this is GQA only): GROUP the einsum instead of
                    # materializing a repeated full-H KV temporary per
                    # decode step — the cache stays Hkv-headed on the
                    # wire and in the dot.
                    g_rep = q.shape[2] // h_cache
                    qg = q.reshape(B, T, h_cache, g_rep, D)
                    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg,
                                   ck.value) / (D ** 0.5)
                    s = jnp.where(m_gqa, s, -jnp.inf)
                    p = jax.nn.softmax(s, axis=-1)
                    o = jnp.einsum("bhgqk,bkhd->bqhgd", p, cv.value)
                    o = o.reshape(B, T, q.shape[2], D)
                else:
                    s = jnp.einsum("bqhd,bkhd->bhqk", q,
                                   ck.value) / (D ** 0.5)
                    s = jnp.where(m_mha, s, -jnp.inf)
                    p = jax.nn.softmax(s, axis=-1)
                    o = jnp.einsum("bhqk,bkhd->bqhd", p, cv.value)
            if ulysses:
                # Heads back together in rank order (= original order).
                o = lax.all_gather(o, self.seq_axis, axis=2, tiled=True)
        elif self.attn_impl == "local":
            o = seqlib.reference_attention(q, k, v, causal=True,
                                           window=self.window)
        elif self.attn_impl == "flash":
            from ..ops.flash import flash_attention_grad

            o = flash_attention_grad(q, k, v, causal=True,
                                     window=self.window)
        elif self.attn_impl == "ring":
            o = seqlib.ring_attention(q, k, v, self.seq_axis, causal=True,
                                      window=self.window)
        elif self.attn_impl == "ring_flash":
            o = seqlib.ring_attention(q, k, v, self.seq_axis, causal=True,
                                      block_impl="flash",
                                      window=self.window)
        elif self.attn_impl == "ulysses":
            o = seqlib.ulysses_attention(q, k, v, self.seq_axis, causal=True,
                                         window=self.window)
        elif self.attn_impl == "ulysses_flash":
            o = seqlib.ulysses_attention(q, k, v, self.seq_axis, causal=True,
                                         block_impl="flash",
                                         window=self.window)
        else:
            raise ValueError(f"unknown attn_impl {self.attn_impl!r}")
        o = o.astype(self.dtype).reshape(B, T, H * D)
        return nn.Dense(E, dtype=self.dtype, name="out")(o)


class MoEMLP(nn.Module):
    """Expert-parallel MLP: tokens routed over ``expert_axis`` with the
    all-to-all dispatch of parallel/expert.py.

    Parameter note: expert weights are declared GLOBAL ([n_experts, ...])
    and each device slices its own block by axis index, so the module works
    under the replicated-params recipes unchanged.  Compute and
    communication are true EP (tokens cross devices, each device runs only
    its experts); parameter MEMORY is not sharded — for memory-scaled EP,
    shard these params over the expert axis via shard_map in_specs instead.

    The device count comes from the axis itself (static at trace time), so
    params can never disagree with the dispatch topology.
    """

    experts_per_device: int
    mlp_ratio: int = 4
    expert_axis: Optional[AxisNames] = None
    capacity_factor: float = 2.0
    k: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):  # x: [B, T, E]
        B, T, E = x.shape
        axes = ((self.expert_axis,) if isinstance(self.expert_axis, str)
                else tuple(self.expert_axis))
        n_devices = 1
        for a in axes:
            n_devices *= lax.axis_size(a)
        n_experts = self.experts_per_device * n_devices
        gate_w = self.param("gate", nn.initializers.lecun_normal(),
                            (E, n_experts), jnp.float32)
        H = E * self.mlp_ratio
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (n_experts, E, H), jnp.float32)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (n_experts, H, E), jnp.float32)
        start = lax.axis_index(axes) * self.experts_per_device
        w1_local = lax.dynamic_slice_in_dim(w1, start,
                                            self.experts_per_device, 0)
        w2_local = lax.dynamic_slice_in_dim(w2, start,
                                            self.experts_per_device, 0)

        def expert_fn(params_e, tokens):
            a, b = params_e
            return jnp.tanh(tokens @ a) @ b

        tokens = x.reshape(B * T, E)
        out, aux = eplib.moe_layer(tokens, gate_w, expert_fn,
                                   (w1_local, w2_local), self.expert_axis,
                                   capacity_factor=self.capacity_factor,
                                   k=self.k, return_aux=True)
        # Per-device load-balance loss, available to training code via
        # model.apply(..., mutable=["losses"]) -> aux["losses"]; scale
        # (typ. 1e-2) and add to the task loss.  Not sown at init so the
        # init-returned variables stay params-only (training code treats
        # them wholesale as optimizer state).
        if not self.is_initializing():
            self.sow("losses", "moe_load_balance", aux)
        return out.reshape(B, T, E).astype(self.dtype)


class Block(nn.Module):
    num_heads: int
    head_dim: int
    mlp_ratio: int = 4
    attn_impl: str = "local"
    seq_axis: Optional[AxisNames] = None
    # When set, the MLP becomes an expert-parallel MoE over this axis.
    moe_axis: Optional[AxisNames] = None
    moe_experts_per_device: int = 1
    moe_capacity_factor: float = 2.0
    moe_k: int = 1
    dtype: jnp.dtype = jnp.float32
    decode: bool = False
    max_len: int = 0
    window: Optional[int] = None
    num_kv_heads: Optional[int] = None
    rope: bool = False

    @nn.compact
    def __call__(self, x, pos_offset=0):
        E = x.shape[-1]
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        x = x + SPAttention(self.num_heads, self.head_dim, self.attn_impl,
                            self.seq_axis, self.dtype, decode=self.decode,
                            max_len=self.max_len, window=self.window,
                            num_kv_heads=self.num_kv_heads,
                            rope=self.rope)(h, pos_offset)
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        if self.moe_axis is not None:
            return x + MoEMLP(self.moe_experts_per_device, self.mlp_ratio,
                              self.moe_axis,
                              capacity_factor=self.moe_capacity_factor,
                              k=self.moe_k, dtype=self.dtype)(h)
        h = nn.Dense(E * self.mlp_ratio, dtype=self.dtype)(h)
        h = nn.gelu(h)
        return x + nn.Dense(E, dtype=self.dtype)(h)


class TransformerLM(nn.Module):
    """Causal LM.  With ``seq_axis``, position embeddings use each shard's
    global offset, supplied as ``pos_offset`` (device-local sequence start)."""

    vocab: int = 256
    embed: int = 128
    depth: int = 2
    num_heads: int = 8
    head_dim: int = 16
    max_len: int = 4096
    attn_impl: str = "local"
    seq_axis: Optional[AxisNames] = None
    moe_axis: Optional[AxisNames] = None
    moe_experts_per_device: int = 1
    moe_capacity_factor: float = 2.0
    moe_k: int = 1
    dtype: jnp.dtype = jnp.float32
    # Autoregressive serving: decode=True switches attention to the KV
    # cache ("cache" collection; see models/generate.py for the loop).
    decode: bool = False
    # Sliding-window attention width (see SPAttention.window).
    window: Optional[int] = None
    # Grouped-query attention kv-head count (see SPAttention.num_kv_heads).
    num_kv_heads: Optional[int] = None
    # Position encoding: "learned" (absolute table, the default) or
    # "rope" (rotary embeddings applied to q/k in every attention layer;
    # no position table - max_len then only bounds the decode cache).
    pos_emb: str = "learned"

    @nn.compact
    def __call__(self, tokens, pos_offset=0, return_prehead: bool = False):
        # tokens: [B, T_local] int32
        B, T = tokens.shape
        x = nn.Embed(self.vocab, self.embed, dtype=self.dtype)(tokens)
        if self.pos_emb == "learned":
            table = nn.Embed(self.max_len, self.embed, dtype=self.dtype,
                             name="pos_embed")
            po = jnp.asarray(pos_offset)
            if po.ndim == 1:
                # Per-row offsets (slot-indexed decode): each batch row
                # embeds its own absolute position.
                x = x + table(po[:, None] + jnp.arange(T)[None])
            else:
                x = x + table(pos_offset + jnp.arange(T))[None]
        elif self.pos_emb != "rope":
            raise ValueError(f"unknown pos_emb {self.pos_emb!r}")
        for _ in range(self.depth):
            x = Block(self.num_heads, self.head_dim,
                      attn_impl=self.attn_impl, seq_axis=self.seq_axis,
                      moe_axis=self.moe_axis,
                      moe_experts_per_device=self.moe_experts_per_device,
                      moe_capacity_factor=self.moe_capacity_factor,
                      moe_k=self.moe_k, dtype=self.dtype,
                      decode=self.decode, max_len=self.max_len,
                      window=self.window,
                      num_kv_heads=self.num_kv_heads,
                      rope=self.pos_emb == "rope")(x, pos_offset)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        # Bias-free explicit unembedding (standard for LMs) so callers can
        # feed (pre-head activations, head matrix) to the fused
        # linear+cross-entropy kernel (ops/xent.py) and never materialize
        # [B*T, vocab] logits.
        head = self.param("head", nn.initializers.lecun_normal(),
                          (self.embed, self.vocab), jnp.float32)
        if return_prehead:
            return x, head
        return x @ head

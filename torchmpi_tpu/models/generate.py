"""Autoregressive generation with a KV cache (serving path).

Beyond-reference (the reference predates LMs — SURVEY.md §6.7): greedy or
temperature sampling from a :class:`TransformerLM`, one fused scan over
prefill + decode.  Each step feeds ONE token through the model in
``decode=True`` mode, where attention appends to per-layer [B, max_len]
key/value caches instead of recomputing the whole prefix — O(T) work per
token instead of O(T²), the standard serving transform.  The whole loop is
one ``lax.scan`` inside one jit: static shapes, no host round-trips.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


@partial(jax.jit, static_argnums=(0, 3))
def _generate_jit(model, params, prompt, steps, temperature, rng):
    B, Tp = prompt.shape
    total = Tp + steps

    # Create the per-layer caches by tracing one decode step shape-only.
    _, cache_vars = model.apply(
        {"params": params}, jnp.zeros((B, 1), jnp.int32),
        mutable=["cache"])
    cache0 = jax.tree.map(jnp.zeros_like, cache_vars["cache"])

    def step(carry, i):
        cache, tok_in, rng = carry
        # tok_in is position i's token: prompt[:, 0] initially, then each
        # step's next_tok (prompt while inside it, sampled after).
        logits, updated = model.apply(
            {"params": params, "cache": cache}, tok_in[:, None],
            pos_offset=i, mutable=["cache"])
        logits = logits[:, 0].astype(jnp.float32)  # [B, vocab]
        rng, sub = jax.random.split(rng)
        sampled = jnp.where(
            temperature > 0.0,
            jax.random.categorical(sub, logits / jnp.maximum(
                temperature, 1e-6)),
            jnp.argmax(logits, axis=-1)).astype(prompt.dtype)
        # The token at position i+1: prompt if still inside it, else the
        # model's sample.
        next_tok = jnp.where(i + 1 < Tp, prompt[:, jnp.minimum(i + 1,
                                                               Tp - 1)],
                             sampled)
        return (updated["cache"], next_tok, rng), next_tok

    init = (cache0, prompt[:, 0], rng)
    _, toks = lax.scan(step, init, jnp.arange(total - 1))
    return jnp.concatenate([prompt[:, :1], toks.T], axis=1)


def generate(model, params, prompt, steps: int, *,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """Generate ``steps`` tokens after ``prompt`` ([B, T_prompt] int).

    ``model`` must be a TransformerLM-like flax module supporting
    ``decode=True`` (single-device attention); pass the TRAINING model —
    this wrapper rebinds it for decoding.  ``temperature=0`` is greedy;
    otherwise softmax sampling at the given temperature using ``rng``.
    Returns the full [B, T_prompt + steps] sequence.
    """
    if prompt.ndim != 2:
        raise ValueError(f"prompt must be [batch, time], got "
                         f"{prompt.shape}")
    total = prompt.shape[1] + steps
    if total > model.max_len:
        raise ValueError(
            f"prompt + steps = {total} exceeds model.max_len "
            f"{model.max_len}")
    if getattr(model, "moe_axis", None) is not None:
        raise ValueError(
            "generate() supports dense MLPs only: moe_axis routing needs "
            "a shard_map mesh axis, which the serving loop does not run "
            "under — decode with moe_axis=None (dense) weights")
    dmodel = model.clone(decode=True)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _generate_jit(dmodel, params, jnp.asarray(prompt), steps,
                         jnp.float32(temperature), rng)

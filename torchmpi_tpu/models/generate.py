"""Autoregressive generation with a KV cache (serving path).

Beyond-reference (the reference predates LMs — SURVEY.md §6.7): greedy or
temperature sampling from a :class:`TransformerLM`, single-forward
PREFILL (the whole prompt fills the KV caches in one batched attention
pass) followed by a ``lax.scan`` DECODE in which each step feeds ONE
token through the model in ``decode=True`` mode, appending to per-layer
[B, max_len] key/value caches instead of recomputing the whole prefix —
O(T) work per token, ``steps`` model dispatches total, all inside one
jit: static shapes, no host round-trips.

Two entry points:

- :func:`generate` — single-device dense decode;
- :func:`generate_parallel` — the same fused scan run under ``shard_map``
  over a device mesh, so expert-parallel MoE models decode with their
  dispatch/combine all-to-all riding the mesh axis exactly as in
  training (tiny per-step capacity — the decode analog of capacity-based
  routing), and the batch can shard over a data axis.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _check_sampling(top_k, top_p):
    """Entry-boundary validation: out-of-range knobs would otherwise
    silently degenerate (top_p=0 masks EVERY logit and categorical then
    emits token 0 forever; top_k=0 indexes the minimum logit)."""
    if top_k is not None and int(top_k) < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < float(top_p) <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def _filter_logits(logits, temperature, top_k, top_p):
    """Restrict sampling support: ``top_k`` keeps the k highest logits,
    ``top_p`` keeps the smallest set whose probability mass (at the given
    temperature, over the top-k-filtered support) reaches p — both
    static, composable (k first, then p), and no-ops for greedy decoding
    (argmax ignores the filtered tail).  One vocab sort serves both
    filters; softmax monotonicity lets the nucleus cut be applied as a
    LOGIT threshold, so no unsorted-probs pass is needed.
    """
    if top_k is None and top_p is None:
        return logits
    V = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    if top_k is not None:
        k = min(int(top_k), V)
        kth = sorted_desc[:, k - 1][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
        sorted_desc = jnp.where(jnp.arange(V)[None, :] < k, sorted_desc,
                                -jnp.inf)
    if top_p is not None:
        sp = jax.nn.softmax(
            sorted_desc / jnp.maximum(temperature, 1e-6), axis=-1)
        cum = jnp.cumsum(sp, axis=-1)
        keep_sorted = (cum - sp) < top_p  # exclusive-cumsum nucleus rule
        # The first sorted entry always survives (cum - sp == 0 there),
        # so the threshold is finite and at least one token remains.
        thresh = jnp.min(jnp.where(keep_sorted, sorted_desc, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits >= thresh, logits, -jnp.inf)
    return logits


def _sample(logits, rng, temperature, top_k, top_p, dtype):
    """Filtered greedy/categorical sampling — the one implementation
    behind every serving path (dense scan, TP, PP), so the
    temperature-0 select and the filter interplay can never diverge
    between them."""
    logits = _filter_logits(logits.astype(jnp.float32), temperature,
                            top_k, top_p)
    return jnp.where(
        temperature > 0.0,
        jax.random.categorical(rng, logits / jnp.maximum(
            temperature, 1e-6)),
        jnp.argmax(logits, axis=-1)).astype(dtype)


def _sample_keys(seeds, idxs):
    """Per-row sampling keys for the serving path: row i's key is
    ``fold_in(PRNGKey(seeds[i]), idxs[i])`` where ``idx`` counts the
    tokens the request has emitted so far.  The key therefore depends
    only on (request seed, token index) — NOT on the slot the session
    landed in, the pool shape, or how many times it was re-routed — so
    a sampled stream is bitwise-reproducible given (seed, prompt) and a
    re-prefilled session continues exactly where the dead replica left
    off."""
    return jax.vmap(lambda s, i: jax.random.fold_in(
        jax.random.PRNGKey(s), i))(seeds, idxs)


def _filter_logits_rows(logits, temps, top_ks, top_ps):
    """Per-ROW dynamic :func:`_filter_logits`: each row carries its own
    (temperature, top_k, top_p) as array operands, so ONE compiled
    executable serves a slot pool mixing greedy and sampled requests.

    Sentinels make the knobs exact no-ops without branching:
    ``top_k <= 0`` means k = V (the k-th highest logit is the minimum,
    and the strict ``<`` mask drops nothing), and ``top_p >= 2.0``
    keeps every sorted entry (cumulative mass never reaches 2), so the
    nucleus threshold lands on the row minimum.  A greedy row filtered
    through both sentinels is bitwise the unfiltered row — asserted in
    tests — which is what keeps the serving path's greedy tokens
    identical to the pre-sampling engine.  Composition order matches
    the static filter: k first, then p over the k-filtered support."""
    V = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    k = jnp.where(top_ks <= 0, V, jnp.clip(top_ks, 1, V))     # [R]
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    logits = jnp.where(logits < kth, -jnp.inf, logits)
    sorted_desc = jnp.where(jnp.arange(V)[None, :] < k[:, None],
                            sorted_desc, -jnp.inf)
    sp = jax.nn.softmax(
        sorted_desc / jnp.maximum(temps, 1e-6)[:, None], axis=-1)
    cum = jnp.cumsum(sp, axis=-1)
    keep_sorted = (cum - sp) < top_ps[:, None]  # exclusive-cumsum rule
    thresh = jnp.min(jnp.where(keep_sorted, sorted_desc, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where(logits >= thresh, logits, -jnp.inf)


def _sample_rows(logits, keys, temps, top_ks, top_ps, dtype):
    """Per-row filtered sampling over [R, V] logits with [R] knob
    arrays and [R] per-row keys (:func:`_sample_keys`): greedy rows
    (temp <= 0) take the argmax of the (no-op-filtered) logits, sampled
    rows a categorical draw at their own temperature.  The serving
    engines route every emitted token — prefill first-token, [S, 1]
    decode, [S, K+1] speculative verify — through this one function."""
    logits = _filter_logits_rows(logits.astype(jnp.float32), temps,
                                 top_ks, top_ps)
    drawn = jax.vmap(jax.random.categorical)(
        keys, logits / jnp.maximum(temps, 1e-6)[:, None])
    return jnp.where(temps > 0.0, drawn,
                     jnp.argmax(logits, axis=-1)).astype(dtype)


def _generate_scan(model, params, prompt, steps, temperature, rng,
                   top_k=None, top_p=None, eos_id=None):
    """Single-forward prefill + scanned decode: traceable anywhere a
    model.apply is — directly under jit (dense path) or inside shard_map
    (parallel path, where the model's collective ops see the mesh axes).

    The whole prompt fills the KV caches in ONE forward (the decode-mode
    attention handles T > 1 with the start-offset causal mask), then the
    remaining tokens decode one at a time under ``lax.scan`` — the old
    Tp + steps - 1 sequential model calls become ``steps`` total, the
    standard serving prefill/decode split (the win is O(Tp) fewer
    dispatches AND one big MXU-friendly attention over the prompt
    instead of Tp tiny ones).
    """
    B, Tp = prompt.shape
    if steps <= 0:
        return prompt

    def sample(logits, rng):  # logits: [B, vocab]
        return _sample(logits, rng, temperature, top_k, top_p,
                       prompt.dtype)

    # Prefill: one pass over the full prompt creates AND fills the KV
    # caches (flax initializes missing mutable collections, so no
    # separate shape-tracing pass).  return_prehead avoids the
    # [B, Tp, vocab] logits matmul — only the last position's logits are
    # needed to sample the first generated token.
    (xs, head), updated = model.apply(
        {"params": params}, prompt, pos_offset=0, return_prehead=True,
        mutable=["cache"])
    rng, sub = jax.random.split(rng)
    first = sample(xs[:, -1] @ head, sub)

    if steps == 1:
        return jnp.concatenate([prompt, first[:, None]], axis=1)

    # EOS stopping: once a row emits eos_id every later position is
    # eos_id-padded (static shapes — the scan always runs `steps` ticks;
    # finished rows just stop changing).
    done0 = (first == eos_id) if eos_id is not None else None

    def step(carry, i):
        cache, tok_in, rng, done = carry
        logits, updated = model.apply(
            {"params": params, "cache": cache}, tok_in[:, None],
            pos_offset=i, mutable=["cache"])
        rng, sub = jax.random.split(rng)
        nxt = sample(logits[:, 0], sub)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
            done = done | (nxt == eos_id)
        return (updated["cache"], nxt, rng, done), nxt

    init = (updated["cache"], first, rng, done0)
    _, toks = lax.scan(step, init, Tp + jnp.arange(steps - 1))
    return jnp.concatenate([prompt, first[:, None], toks.T], axis=1)


@partial(jax.jit, static_argnums=(0, 3, 6, 7, 8))
def _generate_jit(model, params, prompt, steps, temperature, rng,
                  top_k=None, top_p=None, eos_id=None):
    return _generate_scan(model, params, prompt, steps, temperature, rng,
                          top_k=top_k, top_p=top_p, eos_id=eos_id)


def _check_prompt(model, prompt, steps):
    if prompt.ndim != 2:
        raise ValueError(f"prompt must be [batch, time], got "
                         f"{prompt.shape}")
    total = prompt.shape[1] + steps
    if total > model.max_len:
        raise ValueError(
            f"prompt + steps = {total} exceeds model.max_len "
            f"{model.max_len}")


def _beam_expand(lp, fin, ln, step_lp, eos_id, dtype):
    """One beam expansion given per-beam next-token log-probs — the
    trellis bookkeeping shared by the dense/EP/Ulysses beam
    (:func:`_beam_scan`) and the TP beam
    (:func:`.tp_generate.tp_beam_search`), so the finished-beam and
    parent-gather semantics can never diverge between them.

    ``lp/fin/ln``: [B, K] cumulative log-prob / finished flag /
    generated length; ``step_lp``: [B, K, V].  Returns
    ``(new_lp, new_tok, new_fin, new_ln, parent)``."""
    B, K, V = step_lp.shape
    if eos_id is not None:
        # Finished beams: the single finite continuation is eos at +0,
        # so their cumulative score survives top_k unchanged.
        pad_row = jnp.where(jnp.arange(V) == eos_id, 0.0, -jnp.inf)
        step_lp = jnp.where(fin[:, :, None], pad_row[None, None, :],
                            step_lp)
    total = lp[:, :, None] + step_lp             # [B, K, V]
    new_lp, flat = lax.top_k(total.reshape(B, K * V), K)
    parent, new_tok = flat // V, (flat % V).astype(dtype)
    par_fin = jnp.take_along_axis(fin, parent, 1)
    new_ln = jnp.take_along_axis(ln, parent, 1) + \
        jnp.where(par_fin, 0, 1)
    new_fin = par_fin
    if eos_id is not None:
        new_fin = par_fin | (new_tok == eos_id)
    return new_lp, new_tok, new_fin, new_ln, parent


def _beam_backtrack(prompt, top_tok, toks, parents, final_lp, final_len,
                    length_penalty):
    """Reconstruct the best hypothesis through the (token, parent)
    trellis, ranked by the (optionally length-normalized) score."""
    score = final_lp
    if length_penalty:
        score = final_lp / jnp.maximum(
            final_len.astype(jnp.float32), 1.0) ** length_penalty
    best = jnp.argmax(score, axis=-1)            # [B]

    def back(beam, y):
        tok_t, par_t = y
        t = jnp.take_along_axis(tok_t, beam[:, None], 1)[:, 0]
        return jnp.take_along_axis(par_t, beam[:, None], 1)[:, 0], t

    beam0, path = lax.scan(back, best, (toks, parents), reverse=True)
    first = jnp.take_along_axis(top_tok, beam0[:, None], 1)[:, 0]
    return jnp.concatenate([prompt, first[:, None], path.T], axis=1)


def _beam_scan(model, params, prompt, steps, K, eos_id=None,
               length_penalty=0.0):
    """KV-cache beam search: prefill once on B rows, tile the caches to
    B*K beam rows, then scan decode steps keeping the K best
    (cumulative-log-prob) hypotheses per batch row.  Beam reindexing
    gathers cache rows by parent; sequences are reconstructed by a
    reverse scan over the (token, parent) trellis — no history carried
    in the decode loop.

    With ``eos_id``, a beam that emits it is FINISHED: its only legal
    continuation is eos_id at zero added log-prob, so its score freezes
    while other beams keep expanding (the fixed-shape analog of removing
    it from the frontier), and the emitted suffix is eos-padded.  With
    ``length_penalty`` alpha > 0, final hypotheses are ranked by
    ``logprob / len**alpha`` where len counts generated tokens up to and
    including the first eos — plain cumulative log-prob otherwise."""
    B, Tp = prompt.shape
    if steps <= 0:
        return prompt

    (xs, head), updated = model.apply(
        {"params": params}, prompt, pos_offset=0, return_prehead=True,
        mutable=["cache"])
    lp0 = jax.nn.log_softmax((xs[:, -1] @ head).astype(jnp.float32), -1)
    V = lp0.shape[-1]
    top_lp, top_tok = lax.top_k(lp0, K)          # [B, K] initial beams
    top_tok = top_tok.astype(prompt.dtype)
    cache = jax.tree.map(
        lambda c: (jnp.repeat(c, K, axis=0)
                   if c.ndim >= 2 and c.shape[0] == B else c),
        updated["cache"])

    if steps == 1:
        best = top_tok[:, 0]  # top_k sorts descending: beam 0 is argmax
        return jnp.concatenate([prompt, best[:, None]], axis=1)

    fin0 = (top_tok == eos_id) if eos_id is not None else \
        jnp.zeros((B, K), bool)
    len0 = jnp.ones((B, K), jnp.int32)

    def step(carry, i):
        cache, lp, tok, fin, ln = carry          # lp/tok/fin/ln: [B, K]
        logits, updated = model.apply(
            {"params": params, "cache": cache}, tok.reshape(B * K, 1),
            pos_offset=i, mutable=["cache"])
        step_lp = jax.nn.log_softmax(
            logits[:, 0].astype(jnp.float32), -1).reshape(B, K, V)
        new_lp, new_tok, new_fin, new_ln, parent = _beam_expand(
            lp, fin, ln, step_lp, eos_id, prompt.dtype)
        reorder = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
        cache = jax.tree.map(
            lambda c: (c[reorder]
                       if c.ndim >= 2 and c.shape[0] == B * K else c),
            updated["cache"])
        return (cache, new_lp, new_tok, new_fin, new_ln), (new_tok, parent)

    (_, final_lp, _, _, final_len), (toks, parents) = lax.scan(
        step, (cache, top_lp, top_tok, fin0, len0),
        Tp + jnp.arange(steps - 1))

    return _beam_backtrack(prompt, top_tok, toks, parents, final_lp,
                           final_len, length_penalty)


@partial(jax.jit, static_argnums=(0, 3, 4, 5, 6))
def _beam_jit(model, params, prompt, steps, beams, eos_id=None,
              length_penalty=0.0):
    return _beam_scan(model, params, prompt, steps, beams, eos_id=eos_id,
                      length_penalty=length_penalty)


def _check_beams(model, beams):
    if beams < 1:
        raise ValueError(f"beams must be >= 1, got {beams}")
    if getattr(model, "vocab", None) is not None and beams > model.vocab:
        raise ValueError(f"beams {beams} exceeds vocab {model.vocab}")


def beam_search(model, params, prompt, steps: int, *, beams: int,
                eos_id: Optional[int] = None,
                length_penalty: float = 0.0,
                rng=None) -> jax.Array:
    """Beam-search decoding over the KV cache: returns, per batch row,
    the highest-scoring continuation among ``beams`` hypotheses expanded
    per step — ``beams=1`` is exactly greedy :func:`generate`, and with
    ``beams >= vocab`` and ``steps == 2`` it is exhaustive (both
    tested).  With ``eos_id``, beams that emit it finish (frozen score,
    eos-padded suffix); ``length_penalty`` alpha ranks final hypotheses
    by ``logprob / len**alpha`` (0.0 = raw cumulative log-prob).  Same
    single-device dense scope as :func:`generate` — use
    :func:`beam_search_parallel` for expert-parallel / ulysses /
    batch-sharded models; ``rng`` is accepted for signature symmetry and
    unused (beam search is deterministic)."""
    _check_prompt(model, prompt, steps)
    _check_beams(model, beams)
    if getattr(model, "moe_axis", None) is not None:
        raise ValueError(
            "beam_search supports dense MLPs only — use "
            "beam_search_parallel(model, ..., mesh=...) for "
            "expert-parallel decode")
    if (getattr(model, "attn_impl", "local").startswith("ulysses")
            and getattr(model, "seq_axis", None) is not None):
        raise ValueError(
            "ulysses decode needs the mesh axis in scope — use "
            "beam_search_parallel(model, ..., mesh=...) for the "
            "head-sharded-cache serving path")
    del rng
    return _beam_jit(model.clone(decode=True), params,
                     jnp.asarray(prompt), steps, int(beams),
                     None if eos_id is None else int(eos_id),
                     float(length_penalty))


def beam_search_parallel(model, params, prompt, steps: int, *, beams: int,
                         mesh, batch_axis: Optional[str] = None,
                         eos_id: Optional[int] = None,
                         length_penalty: float = 0.0) -> jax.Array:
    """Beam search under ``shard_map`` over ``mesh`` — the beam analog of
    :func:`generate_parallel` (VERDICT r3 #7).

    The decode inherits the model's training-time parallelism: an
    expert-parallel model (``moe_axis``) routes each step's B*K beam
    rows through the same dispatch/combine all-to-all as training, and a
    ulysses model (``seq_axis``) serves from the head-sharded KV cache.
    The per-step beam reindexing is a parent-gather over cache rows;
    batch (and therefore beam) rows live whole on each ``batch_axis``
    shard, and the head/expert dimensions the other axes shard are
    untouched by the gather, so the reorder stays shard-local — no
    cross-device traffic beyond the model's own collectives.  With
    ``batch_axis`` the prompt's leading dim shards over that axis.
    ``eos_id`` / ``length_penalty`` as in :func:`beam_search`.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    _check_prompt(model, prompt, steps)
    _check_beams(model, beams)
    fn = _beam_parallel_fn(model.clone(decode=True), steps, int(beams),
                           mesh, batch_axis,
                           None if eos_id is None else int(eos_id),
                           float(length_penalty))
    b_spec = P(batch_axis) if batch_axis else P()
    prompt = jax.device_put(jnp.asarray(prompt),
                            NamedSharding(mesh, b_spec))
    return fn(params, prompt)


@lru_cache(maxsize=None)
def _beam_parallel_fn(dmodel, steps, beams, mesh, batch_axis, eos_id,
                      length_penalty):
    from jax.sharding import PartitionSpec as P

    b_spec = P(batch_axis) if batch_axis else P()

    def body(params, prompt):
        return _beam_scan(dmodel, params, prompt, steps, beams,
                          eos_id=eos_id, length_penalty=length_penalty)

    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(), b_spec),
        out_specs=b_spec, check_vma=False))


def generate(model, params, prompt, steps: int, *,
             temperature: float = 0.0,
             top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             eos_id: Optional[int] = None,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """Generate ``steps`` tokens after ``prompt`` ([B, T_prompt] int).

    ``model`` must be a TransformerLM-like flax module supporting
    ``decode=True`` (single-device attention); pass the TRAINING model —
    this wrapper rebinds it for decoding.  ``temperature=0`` is greedy;
    otherwise softmax sampling at the given temperature using ``rng``,
    optionally restricted to the ``top_k`` highest-logit tokens and/or
    the ``top_p`` nucleus (smallest set reaching that probability mass).
    With ``eos_id``, rows that emit it stop: every later position is
    eos_id (static shapes — the scan still runs ``steps`` ticks).
    Returns the full [B, T_prompt + steps] sequence.
    """
    _check_prompt(model, prompt, steps)
    _check_sampling(top_k, top_p)
    if getattr(model, "moe_axis", None) is not None:
        raise ValueError(
            "generate() supports dense MLPs only: moe_axis routing needs "
            "a shard_map mesh axis — use generate_parallel(model, ..., "
            "mesh=...) to decode an expert-parallel model")
    if (getattr(model, "attn_impl", "local").startswith("ulysses")
            and getattr(model, "seq_axis", None) is not None):
        raise ValueError(
            "ulysses decode needs the mesh axis in scope — use "
            "generate_parallel(model, ..., mesh=...) for the "
            "head-sharded-cache serving path")
    dmodel = model.clone(decode=True)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _generate_jit(dmodel, params, jnp.asarray(prompt), steps,
                         jnp.float32(temperature), rng, top_k, top_p,
                         None if eos_id is None else int(eos_id))


def generate_parallel(model, params, prompt, steps: int, *, mesh,
                      batch_axis: Optional[str] = None,
                      temperature: float = 0.0,
                      top_k: Optional[int] = None,
                      top_p: Optional[float] = None,
                      eos_id: Optional[int] = None,
                      rng: Optional[jax.Array] = None) -> jax.Array:
    """Sharded generation: the fused prefill+decode scan under
    ``shard_map`` over ``mesh``.

    The decode inherits the model's training-time parallelism: an
    expert-parallel model (``moe_axis`` set) routes each step's tokens
    through the same dispatch/combine all-to-all as training, with the
    per-step expert capacity computed from the tiny decode token count
    (capacity-based routing degrades to near-capacity-1).  With
    ``batch_axis`` the batch dimension additionally shards over that
    mesh axis (the leading prompt dim must divide by its size); sampling
    rngs are folded per-shard so sharded batches don't sample in
    lockstep.  Params are taken replicated (P()).  Returns the full
    [B, T_prompt + steps] sequence, sharded over ``batch_axis`` if set.

    The reference has no serving story at all (SURVEY.md §1: 2016-era
    convnets); this extends the beyond-reference EP/DP training axes to
    inference so a model trained parallel can be sampled parallel.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    _check_prompt(model, prompt, steps)
    _check_sampling(top_k, top_p)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    fn = _parallel_fn(model.clone(decode=True), steps, mesh, batch_axis,
                      top_k, top_p,
                      None if eos_id is None else int(eos_id))
    b_spec = P(batch_axis) if batch_axis else P()
    prompt = jax.device_put(jnp.asarray(prompt),
                            NamedSharding(mesh, b_spec))
    return fn(params, prompt, jnp.float32(temperature), rng)


# ---------------------------------------------------------------------------
# Slot-indexed cache plumbing (the continuous-batching serving path,
# torchmpi_tpu/serving/ — docs/SERVING.md).  Four primitives over a
# POOL cache whose batch dimension is the slot dimension:
#
# - :func:`slot_prefill`    — one request's prompt onto a FRESH [1, L]
#   cache (the same single-forward prefill + last-position sampling as
#   :func:`_generate_scan`, so tokens can never diverge from ``generate``);
#   with ``true_len`` the prompt may be right-PADDED to a length bucket
#   — the logits are sliced at the true last position, so padded and
#   unpadded prefill emit bitwise-identical tokens while the compile
#   count drops from O(distinct lengths) to O(buckets);
# - :func:`slot_write`      — copy that request's cache rows into pool
#   row ``slot`` (admission);
# - :func:`slot_decode_step` — ONE [S, 1] decode tick advancing every
#   active slot at its own depth (per-row ``pos_offset`` — see
#   ``SPAttention``); rows beyond a slot's filled prefix are masked, so
#   REUSING a retired slot needs no zeroing to stay bit-identical to a
#   fresh static-batch decode;
# - :func:`slot_verify_step` — the speculative-decoding verify: ONE
#   [S, K+1] forward scoring each slot's pending token plus its K draft
#   tokens at per-row depths, returning what the model samples at EVERY
#   position — the accept/reject scan over those samples is host-side
#   (serving/engine.py) and distribution-exact by construction.
#
# Sampling: each primitive takes a ``sampling`` operand tuple
# ``(seeds, idxs, temps, top_ks, top_ps)`` ([R] arrays) routed through
# :func:`_sample_rows` — greedy rows use the no-op sentinels (temp 0,
# top_k 0, top_p 2.0) and stay bitwise-deterministic, which is what
# keeps re-routing token-exact: a re-prefilled session re-derives the
# same per-token keys from (seed, token index).
# ---------------------------------------------------------------------------


def clamp_slot_positions(positions, limit, width=1):
    """THE cache-index clamp chokepoint: bound ``positions`` (scalar or
    [S]) to ``[0, limit - width]`` so a width-``width``
    ``dynamic_update_slice``/``dynamic_slice`` at each position provably
    stays inside a ``limit``-deep buffer.  For valid inputs (the only
    inputs correct callers produce — serving/engine.py clamps host-side)
    this is bitwise the identity; what it buys is the PROOF: an
    out-of-range start otherwise CLAMPS silently (corrupt last rows, no
    error — the PR 17 bug class), and the static analyzer's S1 rule can
    only certify a write whose index is visibly bounded.  Every cache
    write in ``transformer.SPAttention`` decode and the TP decode blocks
    routes through here; S2 flags per-row slot writes that don't (the
    trace record below is its evidence).
    """
    from .. import fusion

    limit, width = int(limit), int(width)
    fusion._emit_trace_record(
        {"kind": "slot_clamp", "limit": limit, "width": width})
    return jnp.clip(jnp.asarray(positions), 0, max(0, limit - width))


def _greedy_sampling(n):
    """Sentinel sampling arrays for n rows: greedy, filter no-ops."""
    return (jnp.zeros((n,), jnp.uint32), jnp.zeros((n,), jnp.int32),
            jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.int32),
            jnp.full((n,), 2.0, jnp.float32))


@partial(jax.jit, static_argnums=(0,))
def _slot_prefill_jit(dmodel, params, prompt, true_len, seeds, idxs,
                      temps, top_ks, top_ps):
    (xs, head), updated = dmodel.apply(
        {"params": params}, prompt, pos_offset=0, return_prehead=True,
        mutable=["cache"])
    # The TRUE last position, not -1: with bucketed prefill the prompt
    # is right-padded, and the pad positions' logits must never be
    # sampled.  (Causality makes the real positions' activations
    # independent of the padding, so the sliced logits are bitwise the
    # unpadded ones; the pad positions' k/v land in the cache but every
    # later query is depth-masked below them until the decode steps
    # overwrite them in order.)
    x_last = lax.dynamic_slice_in_dim(
        xs, clamp_slot_positions(true_len - 1, xs.shape[1]), 1,
        axis=1)[:, 0]
    first = _sample_rows(x_last @ head, _sample_keys(seeds, idxs),
                         temps, top_ks, top_ps, prompt.dtype)
    return updated["cache"], first


def slot_prefill(dmodel, params, prompt, *, true_len=None,
                 sampling=None):
    """Prefill one request ([1, Tp] prompt, possibly right-padded to a
    length bucket) on a fresh cache; returns ``(cache, first_token
    [1])``.  ``dmodel`` is the ``decode=True`` clone (one jit
    specialization per PADDED prompt length — ``true_len`` is a traced
    operand, so every length in a bucket shares the executable).
    ``sampling`` is the 5-tuple of [1] arrays; None means greedy."""
    prompt = jnp.asarray(prompt)
    if true_len is None:
        true_len = prompt.shape[1]
    if sampling is None:
        sampling = _greedy_sampling(prompt.shape[0])
    return _slot_prefill_jit(dmodel, params, prompt,
                             jnp.asarray(true_len, jnp.int32), *sampling)


@partial(jax.jit, static_argnums=(0,))
def _slot_step_jit(dmodel, params, cache, tokens, positions, seeds,
                   idxs, temps, top_ks, top_ps):
    logits, updated = dmodel.apply(
        {"params": params, "cache": cache}, tokens[:, None],
        pos_offset=positions, mutable=["cache"])
    nxt = _sample_rows(logits[:, 0], _sample_keys(seeds, idxs), temps,
                       top_ks, top_ps, tokens.dtype)
    return updated["cache"], nxt


def slot_decode_step(dmodel, params, cache, tokens, positions,
                     sampling=None):
    """One decode tick over the whole slot pool: ``tokens`` [S] are each
    slot's pending token, ``positions`` [S] its absolute write index
    (inactive slots pass any valid filler — their outputs are ignored
    and their cache rows are fully overwritten on the next admission).
    Returns ``(new_cache, next_tokens [S])``.  One compiled executable
    serves the entire trace — admission, retirement, and greedy/sampled
    mixes never retrace (the sampling knobs are [S] operands)."""
    tokens = jnp.asarray(tokens)
    if sampling is None:
        sampling = _greedy_sampling(tokens.shape[0])
    return _slot_step_jit(dmodel, params, cache, tokens,
                          jnp.asarray(positions), *sampling)


@partial(jax.jit, static_argnums=(0,))
def _slot_verify_jit(dmodel, params, cache, tokens, positions, seeds,
                     idxs, temps, top_ks, top_ps):
    logits, updated = dmodel.apply(
        {"params": params, "cache": cache}, tokens,
        pos_offset=positions, mutable=["cache"])
    S, T, V = logits.shape
    # Position j of row s samples with key (seed_s, idx_s + j): exactly
    # the key the NON-speculative path would use for that token index,
    # which is what makes accept-until-mismatch emit a bitwise-identical
    # stream (each kept sample conditions on an accepted prefix, i.e.
    # the same context the sequential path would have fed).
    keys = _sample_keys(
        jnp.repeat(seeds, T),
        (idxs[:, None] + jnp.arange(T, dtype=jnp.int32)).reshape(-1))
    flat = _sample_rows(logits.reshape(S * T, V), keys,
                        jnp.repeat(temps, T), jnp.repeat(top_ks, T),
                        jnp.repeat(top_ps, T), tokens.dtype)
    return updated["cache"], flat.reshape(S, T)


def slot_verify_step(dmodel, params, cache, tokens, positions,
                     sampling=None):
    """The speculative-decoding verify forward: ``tokens`` [S, K+1] is
    each slot's pending token followed by its K draft tokens,
    ``positions`` [S] each slot's write index.  One forward writes all
    K+1 k/v entries at per-row depths and returns the model's sample at
    EVERY position ([S, K+1]) — sample j is the token the sequential
    decode would emit after the fed prefix ``tokens[:, :j+1]``, so the
    host-side scan "accept while draft matches, then take the model's
    corrected token" reproduces non-speculative decoding bit for bit.
    Rejected positions leave stale k/v behind; the next forward for
    that row starts at its accepted depth and re-writes them before any
    query can attend (same-forward cache update precedes attention),
    so no masking bookkeeping is needed."""
    tokens = jnp.asarray(tokens)
    if sampling is None:
        sampling = _greedy_sampling(tokens.shape[0])
    return _slot_verify_jit(dmodel, params, cache, tokens,
                            jnp.asarray(positions), *sampling)


@jax.jit
def _slot_write_jit(pool_cache, one_cache, slot):
    pooled = [p for p in jax.tree.leaves(pool_cache)
              if getattr(p, "ndim", 0) >= 1]
    if pooled:
        slot = clamp_slot_positions(slot, pooled[0].shape[0])

    def put(p, o):
        if getattr(o, "ndim", 0) >= 1 and o.shape[0] == 1 \
                and p.ndim == o.ndim:
            return lax.dynamic_update_slice(
                p, o.astype(p.dtype), (slot,) + (0,) * (p.ndim - 1))
        return p  # scalar cache leaves (the unused idx counter)

    return jax.tree.map(put, pool_cache, one_cache)


def slot_write(pool_cache, one_cache, slot: int):
    """Copy a :func:`slot_prefill` cache (leading dim 1) into row
    ``slot`` of the pool cache (leading dim = slot count)."""
    return _slot_write_jit(pool_cache, one_cache,
                           jnp.asarray(slot, jnp.int32))


# ---------------------------------------------------------------------------
# Prefix-cache fragment primitives (serving/prefix_cache.py).  A
# "fragment" is a width-W token-axis slice of a single-row cache — the
# k/v a shared prompt prefix produced.  Causality + absolute-position
# rope make a prefix's k/v depend ONLY on the prefix tokens, so a
# fragment sliced from one request's prefill is bitwise the fragment
# every later request sharing that prefix would have computed; writing
# it back and running :func:`slot_extend` over just the unshared suffix
# reproduces the full prefill bit for bit (the per-row depth mask hides
# everything beyond the assembled depth, exactly the argument that
# already covers slot reuse and bucketed-prefill padding).
#
# Both helpers are layout-generic pytree maps: a leaf participates iff
# it looks like a per-row cache plane — ``ndim >= 2`` with a leading
# row dim of 1 (token axis 1).  That covers the dense flax cache dict
# ([1, max_len, heads, dim] k/v) and the TP list-of-(k, v) pairs alike;
# the dense cache's scalar ``idx`` counter falls through untouched.
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(2,))
def _slot_cache_slice_jit(row_cache, start, width):
    def cut(p):
        if getattr(p, "ndim", 0) >= 2 and p.shape[0] == 1:
            return lax.dynamic_slice_in_dim(
                p, clamp_slot_positions(start, p.shape[1], width),
                width, axis=1)
        return p
    return jax.tree.map(cut, row_cache)


def slot_cache_slice(row_cache, start: int, width: int):
    """Slice ``width`` token positions starting at ``start`` out of a
    single-row cache — the fragment a prefix-cache node stores."""
    return _slot_cache_slice_jit(row_cache,
                                 jnp.asarray(start, jnp.int32),
                                 int(width))


@jax.jit
def _slot_cache_write_jit(row_cache, frag, start):
    def put(p, f):
        if getattr(f, "ndim", 0) >= 2 and f.shape[0] == 1 \
                and p.ndim == f.ndim:
            pos = clamp_slot_positions(start, p.shape[1], f.shape[1])
            return lax.dynamic_update_slice(
                p, f.astype(p.dtype),
                (0, pos) + (0,) * (p.ndim - 2))
        return p
    return jax.tree.map(put, row_cache, frag)


def slot_cache_write(row_cache, frag, start: int):
    """Write a :func:`slot_cache_slice` fragment back into a single-row
    cache at token position ``start`` (cache-hit row assembly)."""
    return _slot_cache_write_jit(row_cache, frag,
                                 jnp.asarray(start, jnp.int32))


@partial(jax.jit, static_argnums=(0,))
def _slot_extend_jit(dmodel, params, row_cache, suffix, pos_offset,
                     true_len, seeds, idxs, temps, top_ks, top_ps):
    (xs, head), updated = dmodel.apply(
        {"params": params, "cache": row_cache}, suffix,
        pos_offset=pos_offset, return_prehead=True, mutable=["cache"])
    # true_len is SUFFIX-local: the true last position within the
    # (possibly right-padded) suffix block, same bucketing contract as
    # _slot_prefill_jit.
    x_last = lax.dynamic_slice_in_dim(
        xs, clamp_slot_positions(true_len - 1, xs.shape[1]), 1,
        axis=1)[:, 0]
    first = _sample_rows(x_last @ head, _sample_keys(seeds, idxs),
                         temps, top_ks, top_ps, suffix.dtype)
    return updated["cache"], first


def slot_extend(dmodel, params, row_cache, suffix, *, pos_offset,
                true_len=None, sampling=None):
    """Prefill only the unshared SUFFIX of a prompt over a single-row
    cache pre-assembled from prefix-cache fragments; returns
    ``(cache, first_token [1])``.

    ``suffix`` is [1, Ts] (right-padded to a bucket like
    :func:`slot_prefill`; ``true_len`` is the suffix's true length),
    ``pos_offset`` the [1] absolute depth of the assembled prefix.  The
    1-D per-row offset with T > 1 takes the same cache-masked attention
    branch the speculative verify forward uses: queries attend the
    assembled fragments plus the in-flight suffix and nothing deeper —
    exactly the positions a full prefill's causal mask admits — and the
    sampling key is ``(seed, idx)`` with idx = the prompt's global
    token count, so a cache hit leaves the ``fold_in`` schedule
    untouched and the emitted stream bitwise-identical to a miss (and
    to offline ``generate``)."""
    suffix = jnp.asarray(suffix)
    if true_len is None:
        true_len = suffix.shape[1]
    if sampling is None:
        sampling = _greedy_sampling(suffix.shape[0])
    return _slot_extend_jit(dmodel, params, row_cache, suffix,
                            jnp.asarray(pos_offset, jnp.int32),
                            jnp.asarray(true_len, jnp.int32), *sampling)


@lru_cache(maxsize=None)
def _parallel_fn(dmodel, steps, mesh, batch_axis, top_k=None, top_p=None,
                 eos_id=None):
    """Build (once per (model, steps, mesh, batch_axis, filters)) the
    jitted shard_map serving fn — a fresh closure per call would retrace
    and recompile the whole scan every invocation; temperature and rng
    stay operands so greedy/sampled calls share the executable."""
    from jax.sharding import PartitionSpec as P

    b_spec = P(batch_axis) if batch_axis else P()

    def body(params, prompt, temperature, rng):
        if batch_axis is not None:
            rng = jax.random.fold_in(rng, lax.axis_index(batch_axis))
        return _generate_scan(dmodel, params, prompt, steps,
                              temperature, rng, top_k=top_k, top_p=top_p,
                              eos_id=eos_id)

    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(), b_spec, P(), P()),
        out_specs=b_spec, check_vma=False))

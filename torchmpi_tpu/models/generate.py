"""Autoregressive generation with a KV cache (serving path).

Beyond-reference (the reference predates LMs — SURVEY.md §6.7): greedy or
temperature sampling from a :class:`TransformerLM`, one fused scan over
prefill + decode.  Each step feeds ONE token through the model in
``decode=True`` mode, where attention appends to per-layer [B, max_len]
key/value caches instead of recomputing the whole prefix — O(T) work per
token instead of O(T²), the standard serving transform.  The whole loop is
one ``lax.scan`` inside one jit: static shapes, no host round-trips.

Two entry points:

- :func:`generate` — single-device dense decode;
- :func:`generate_parallel` — the same fused scan run under ``shard_map``
  over a device mesh, so expert-parallel MoE models decode with their
  dispatch/combine all-to-all riding the mesh axis exactly as in
  training (tiny per-step capacity — the decode analog of capacity-based
  routing), and the batch can shard over a data axis.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _generate_scan(model, params, prompt, steps, temperature, rng):
    """The fused prefill+decode loop: traceable anywhere a model.apply
    is — directly under jit (dense path) or inside shard_map (parallel
    path, where the model's collective ops see the mesh axes)."""
    B, Tp = prompt.shape
    total = Tp + steps

    # Create the per-layer caches by tracing one decode step shape-only.
    _, cache_vars = model.apply(
        {"params": params}, jnp.zeros((B, 1), jnp.int32),
        mutable=["cache"])
    cache0 = jax.tree.map(jnp.zeros_like, cache_vars["cache"])

    def step(carry, i):
        cache, tok_in, rng = carry
        # tok_in is position i's token: prompt[:, 0] initially, then each
        # step's next_tok (prompt while inside it, sampled after).
        logits, updated = model.apply(
            {"params": params, "cache": cache}, tok_in[:, None],
            pos_offset=i, mutable=["cache"])
        logits = logits[:, 0].astype(jnp.float32)  # [B, vocab]
        rng, sub = jax.random.split(rng)
        sampled = jnp.where(
            temperature > 0.0,
            jax.random.categorical(sub, logits / jnp.maximum(
                temperature, 1e-6)),
            jnp.argmax(logits, axis=-1)).astype(prompt.dtype)
        # The token at position i+1: prompt if still inside it, else the
        # model's sample.
        next_tok = jnp.where(i + 1 < Tp, prompt[:, jnp.minimum(i + 1,
                                                               Tp - 1)],
                             sampled)
        return (updated["cache"], next_tok, rng), next_tok

    init = (cache0, prompt[:, 0], rng)
    _, toks = lax.scan(step, init, jnp.arange(total - 1))
    return jnp.concatenate([prompt[:, :1], toks.T], axis=1)


@partial(jax.jit, static_argnums=(0, 3))
def _generate_jit(model, params, prompt, steps, temperature, rng):
    return _generate_scan(model, params, prompt, steps, temperature, rng)


def _check_prompt(model, prompt, steps):
    if prompt.ndim != 2:
        raise ValueError(f"prompt must be [batch, time], got "
                         f"{prompt.shape}")
    total = prompt.shape[1] + steps
    if total > model.max_len:
        raise ValueError(
            f"prompt + steps = {total} exceeds model.max_len "
            f"{model.max_len}")


def generate(model, params, prompt, steps: int, *,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """Generate ``steps`` tokens after ``prompt`` ([B, T_prompt] int).

    ``model`` must be a TransformerLM-like flax module supporting
    ``decode=True`` (single-device attention); pass the TRAINING model —
    this wrapper rebinds it for decoding.  ``temperature=0`` is greedy;
    otherwise softmax sampling at the given temperature using ``rng``.
    Returns the full [B, T_prompt + steps] sequence.
    """
    _check_prompt(model, prompt, steps)
    if getattr(model, "moe_axis", None) is not None:
        raise ValueError(
            "generate() supports dense MLPs only: moe_axis routing needs "
            "a shard_map mesh axis — use generate_parallel(model, ..., "
            "mesh=...) to decode an expert-parallel model")
    if (getattr(model, "attn_impl", "local").startswith("ulysses")
            and getattr(model, "seq_axis", None) is not None):
        raise ValueError(
            "ulysses decode needs the mesh axis in scope — use "
            "generate_parallel(model, ..., mesh=...) for the "
            "head-sharded-cache serving path")
    dmodel = model.clone(decode=True)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _generate_jit(dmodel, params, jnp.asarray(prompt), steps,
                         jnp.float32(temperature), rng)


def generate_parallel(model, params, prompt, steps: int, *, mesh,
                      batch_axis: Optional[str] = None,
                      temperature: float = 0.0,
                      rng: Optional[jax.Array] = None) -> jax.Array:
    """Sharded generation: the fused prefill+decode scan under
    ``shard_map`` over ``mesh``.

    The decode inherits the model's training-time parallelism: an
    expert-parallel model (``moe_axis`` set) routes each step's tokens
    through the same dispatch/combine all-to-all as training, with the
    per-step expert capacity computed from the tiny decode token count
    (capacity-based routing degrades to near-capacity-1).  With
    ``batch_axis`` the batch dimension additionally shards over that
    mesh axis (the leading prompt dim must divide by its size); sampling
    rngs are folded per-shard so sharded batches don't sample in
    lockstep.  Params are taken replicated (P()).  Returns the full
    [B, T_prompt + steps] sequence, sharded over ``batch_axis`` if set.

    The reference has no serving story at all (SURVEY.md §1: 2016-era
    convnets); this extends the beyond-reference EP/DP training axes to
    inference so a model trained parallel can be sampled parallel.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    _check_prompt(model, prompt, steps)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    fn = _parallel_fn(model.clone(decode=True), steps, mesh, batch_axis)
    b_spec = P(batch_axis) if batch_axis else P()
    prompt = jax.device_put(jnp.asarray(prompt),
                            NamedSharding(mesh, b_spec))
    return fn(params, prompt, jnp.float32(temperature), rng)


@lru_cache(maxsize=None)
def _parallel_fn(dmodel, steps, mesh, batch_axis):
    """Build (once per (model, steps, mesh, batch_axis)) the jitted
    shard_map serving fn — a fresh closure per call would retrace and
    recompile the whole scan every invocation; temperature and rng stay
    operands so greedy/sampled calls share the executable."""
    from jax.sharding import PartitionSpec as P

    b_spec = P(batch_axis) if batch_axis else P()

    def body(params, prompt, temperature, rng):
        if batch_axis is not None:
            rng = jax.random.fold_in(rng, lax.axis_index(batch_axis))
        return _generate_scan(dmodel, params, prompt, steps,
                              temperature, rng)

    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(), b_spec, P(), P()),
        out_specs=b_spec, check_vma=False))

"""AlexNet — the reference's async Downpour-SGD workload (SURVEY.md §8.1
config 4, reconstructed — reference mount empty).

TPU-first notes: NHWC, SAME padding, channel counts kept as upstream AlexNet
(the MXU tiles 64/128-multiples best; AlexNet's 96/256/384 channels are close
enough that XLA pads without measurable waste at these sizes).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class AlexNet(nn.Module):
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):  # x: [B, 224, 224, 3]
        x = x.astype(self.dtype)
        x = nn.Conv(96, (11, 11), (4, 4), padding="SAME",
                    dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(256, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(384, (3, 3), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(384, (3, 3), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(256, (3, 3), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(4096, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(4096, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x

"""ResNet family: CIFAR ResNet-20 and ImageNet ResNet-50.

Reference workloads (SURVEY.md §8.1, reconstructed — reference mount empty):
the reference integrated with ``fb.resnet.torch`` for CIFAR/ImageNet
data-parallel training [HIGH].  This is a TPU-first reimplementation of the
same model family, not a port: NHWC layouts, bfloat16 compute with float32
params/statistics (MXU-friendly), BatchNorm running statistics kept in a
separate ``batch_stats`` collection so the data-parallel step can
cross-replica average them.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/20/34 style)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (ResNet-50/101/152 style)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """Generic ResNet over NHWC inputs.

    ``stem``: "imagenet" (7x7/2 conv + 3x3/2 maxpool) or "cifar" (3x3 conv).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int
    num_filters: int = 64
    stem: str = "imagenet"
    dtype: jnp.dtype = jnp.float32
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       padding="SAME")
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        if self.stem == "imagenet":
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = self.act(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        else:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = self.act(x)
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    conv=conv, norm=norm, act=self.act, strides=strides,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def ResNet20(num_classes: int = 10, dtype=jnp.float32) -> ResNet:
    """CIFAR ResNet-20: 3 stages x 3 basic blocks, 16 base filters."""
    return ResNet(stage_sizes=[3, 3, 3], block_cls=BasicBlock,
                  num_classes=num_classes, num_filters=16, stem="cifar",
                  dtype=dtype)


def ResNet18(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock,
                  num_classes=num_classes, dtype=dtype)


def ResNet50(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    """ImageNet ResNet-50: [3, 4, 6, 3] bottlenecks — the headline workload
    (BASELINE.md: >=90% scaling efficiency on v5e-64)."""
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock,
                  num_classes=num_classes, dtype=dtype)


def ResNet101(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    """ImageNet ResNet-101: [3, 4, 23, 3] bottlenecks (fb.resnet.torch's
    deeper preset)."""
    return ResNet(stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock,
                  num_classes=num_classes, dtype=dtype)


def ResNet152(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    """ImageNet ResNet-152: [3, 8, 36, 3] bottlenecks."""
    return ResNet(stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock,
                  num_classes=num_classes, dtype=dtype)

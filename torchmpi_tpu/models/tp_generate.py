"""Tensor-parallel serving: prefill + scanned decode for the Megatron
TP stack.

VERDICT r3 missing #5 noted serving existed for the dense, EP and
Ulysses paths but not TP.  This module decodes with the SAME layer math
as TP training (:mod:`..parallel.tensor`): attention heads and MLP
features shard over the model axis, costing one psum per sublayer per
token, plus one tiled ``all_gather`` of the column-parallel LM head's
vocab slices per sampled token.  The KV cache is head-local — each
device caches only its own heads, so cache memory also scales 1/n with
the model axis (the point of TP serving: models whose KV cache or
weights exceed one chip).

The reference has no serving story at all (SURVEY.md §1 — 2016-era
convnets); like the rest of ``models/generate.py`` this is
beyond-reference surface built on the reference-mandated communicator
design (§6.7: the mesh must not preclude a model axis).

Sampling semantics (greedy/temperature/top-k/top-p via
``generate._filter_logits``, EOS freeze) mirror ``_generate_scan`` so
the serving surface behaves identically across parallel paths.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..parallel import tensor as tp
from .generate import _beam_backtrack, _beam_expand, _check_sampling, \
    _greedy_sampling, _sample, _sample_keys, _sample_rows, \
    clamp_slot_positions
from .transformer import apply_rope


def init_tp_lm(rng, *, vocab: int, embed: int, depth: int, num_heads: int,
               head_dim: Optional[int] = None, mlp_ratio: int = 4,
               dtype=jnp.float32):
    """Full (unsharded) parameter tree for the TP decode stack — the
    same per-block layout :func:`..parallel.tensor.tp_transformer_block`
    consumes (ln1/ln2, wq/wk/wv/wo, w1/w2), plus ``embed`` [V, D],
    ``ln_f`` and the untied ``head`` [D, V].  Shard with
    :func:`shard_tp_lm`; scale is 1/sqrt(fan_in) so logits stay sane at
    serving depth."""
    D, hd = embed, head_dim or embed // num_heads
    width, hidden = num_heads * hd, mlp_ratio * embed
    ks = jax.random.split(rng, 2 + 6 * depth)  # 6 dense weights/block

    def dense(k, din, dout):
        return (jax.random.normal(k, (din, dout), jnp.float32)
                / np.sqrt(din)).astype(dtype)

    blocks = []
    for layer in range(depth):
        k = ks[2 + 6 * layer:8 + 6 * layer]
        blocks.append({
            "ln1": (jnp.ones((D,), dtype), jnp.zeros((D,), dtype)),
            "ln2": (jnp.ones((D,), dtype), jnp.zeros((D,), dtype)),
            "wq": dense(k[0], D, width), "wk": dense(k[1], D, width),
            "wv": dense(k[2], D, width), "wo": dense(k[3], width, D),
            "w1": dense(k[4], D, hidden), "w2": dense(k[5], hidden, D),
        })
    return {"embed": dense(ks[0], vocab, D),  # [V, D] table
            "blocks": blocks,
            "ln_f": (jnp.ones((D,), dtype), jnp.zeros((D,), dtype)),
            "head": dense(ks[1], D, vocab)}


def _tp_specs(depth, axis):
    """PartitionSpec tree matching :func:`shard_tp_lm`'s placement."""
    from jax.sharding import PartitionSpec as P

    col, row, rep = P(None, axis), P(axis, None), P()
    return {
        "embed": rep,
        "blocks": [{"ln1": (rep, rep), "ln2": (rep, rep),
                    "wq": col, "wk": col, "wv": col, "wo": row,
                    "w1": col, "w2": row} for _ in range(depth)],
        "ln_f": (rep, rep),
        "head": col,
    }


def shard_tp_lm(params, mesh, axis):
    """Place a full tree from :func:`init_tp_lm` on ``mesh``: qkv/w1 and
    the LM head column-sharded over ``axis``, wo/w2 row-sharded,
    embeddings and norms replicated.  Returns ``(sharded_params,
    spec_tree)`` — the spec tree doubles as the shard_map ``in_specs``
    entry (mirrors :func:`..parallel.tensor.shard_columns` placement
    without host-side slicing: jax moves the shards)."""
    from jax.sharding import NamedSharding, PartitionSpec

    specs = _tp_specs(len(params["blocks"]), axis)
    # Map over the SPEC tree with PartitionSpec pinned as a leaf —
    # PartitionSpec subclasses tuple, so mapping over the param tree
    # would descend into the specs instead of pairing them.
    placed = jax.tree.map(
        lambda s, v: jax.device_put(v, NamedSharding(mesh, s)),
        specs, params,
        is_leaf=lambda x: isinstance(x, PartitionSpec))
    return placed, specs


def _ln(h, scale, bias):
    mu = h.mean(-1, keepdims=True)
    var = ((h - mu) ** 2).mean(-1, keepdims=True)
    return (h - mu) * lax.rsqrt(var + 1e-6) * scale + bias


def _qkv_local(x, p, axis, num_heads, pos):
    """Project to this device's local heads and rotate by absolute
    ``pos`` ([T] int32, may be traced).  x: [B, T, D] replicated."""
    B, T, _ = x.shape
    n = 1
    for a in (axis if isinstance(axis, tuple) else (axis,)):
        n *= lax.axis_size(a)
    if num_heads % n:
        raise ValueError(f"num_heads {num_heads} must divide by the "
                         f"model-axis size {n}")
    hl = num_heads // n
    xr = tp.f_identity(x, axis)
    width = p["wq"].shape[-1]
    dh = width // hl
    q = (xr @ p["wq"]).reshape(B, T, hl, dh)
    k = (xr @ p["wk"]).reshape(B, T, hl, dh)
    v = (xr @ p["wv"]).reshape(B, T, hl, dh)
    q, k = apply_rope(q, pos), apply_rope(k, pos)
    return q, k, v, width, dh


def _block_prefill(x, p, axis, num_heads, t_max):
    """Causal attention over the whole prompt, returning this block's
    output and the head-local KV cache padded to ``t_max``.  Dense
    O(Tp^2) scores — serving prompts are short; long-context prefill
    belongs to the flash/ring training paths."""
    B, T, _ = x.shape
    h = _ln(x, *p["ln1"])
    q, k, v, width, dh = _qkv_local(h, p, axis, num_heads,
                                    jnp.arange(T, dtype=jnp.int32))
    scores = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(dh)
    scores = jnp.where(jnp.tril(jnp.ones((T, T), bool)), scores,
                       jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32),
                           axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(B, T, width)
    x = x + tp.row_parallel_dense(ctx, p["wo"], axis)
    m = tp.tp_mlp(_ln(x, *p["ln2"]), p["w1"], p["w2"], axis,
                  act=jax.nn.gelu)
    pad = [(0, 0), (0, t_max - T), (0, 0), (0, 0)]
    return x + m, (jnp.pad(k, pad), jnp.pad(v, pad))


def _block_decode(x, p, cache, pos, axis, num_heads):
    """One-token decode: append this token's head-local k/v at ``pos``
    and attend over the valid cache prefix.  x: [B, 1, D]."""
    ck, cv = cache
    B = x.shape[0]
    t_max = ck.shape[1]
    # The clamp chokepoint (generate.clamp_slot_positions): identity in
    # the valid range, makes the writes below S1-certifiable.
    pos = clamp_slot_positions(pos, t_max)
    h = _ln(x, *p["ln1"])
    q, k1, v1, width, dh = _qkv_local(h, p, axis, num_heads, pos[None])
    ck = lax.dynamic_update_slice(ck, k1, (0, pos, 0, 0))
    cv = lax.dynamic_update_slice(cv, v1, (0, pos, 0, 0))
    scores = jnp.einsum("bthd,bshd->bhts", q, ck) / np.sqrt(dh)
    valid = (jnp.arange(t_max) <= pos)[None, None, None, :]
    scores = jnp.where(valid, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32),
                           axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhts,bshd->bthd", probs, cv).reshape(B, 1, width)
    x = x + tp.row_parallel_dense(ctx, p["wo"], axis)
    m = tp.tp_mlp(_ln(x, *p["ln2"]), p["w1"], p["w2"], axis,
                  act=jax.nn.gelu)
    return x + m, (ck, cv)


def _block_decode_rows(x, p, cache, pos_rows, axis, num_heads):
    """Per-ROW decode over the slot pool: x [S, T, D] — row ``s`` writes
    its T tokens' head-local k/v at ``pos_rows[s] .. pos_rows[s]+T-1``
    (each slot at its OWN cache depth) and attends its own causal
    prefix.  T == 1 is the continuous-batching tick; T == K+1 is the
    speculative verify.  The mirror of the dense per-row ``pos_offset``
    path in ``transformer.SPAttention`` with head-local caches."""
    ck, cv = cache
    S, T, _ = x.shape
    t_max = ck.shape[1]
    # Per-row clamp chokepoint: the vmapped update below lowers to a
    # mode=CLIP scatter, which silently corrupts on an out-of-range
    # row position — clamped positions are S1/S2-certifiable.
    pos_rows = clamp_slot_positions(pos_rows, t_max, T)
    h = _ln(x, *p["ln1"])
    q_pos = pos_rows[:, None] + jnp.arange(T, dtype=jnp.int32)  # [S, T]
    q, k1, v1, width, dh = _qkv_local(h, p, axis, num_heads, q_pos)
    row_upd = jax.vmap(
        lambda c, u, s: lax.dynamic_update_slice(c, u, (s, 0, 0)))
    ck = row_upd(ck, k1, pos_rows)
    cv = row_upd(cv, v1, pos_rows)
    scores = jnp.einsum("bthd,bshd->bhts", q, ck) / np.sqrt(dh)
    # [S, 1, T, t_max]: query t of row s sees cache entries <= its own
    # absolute position — stale rows from retired slots mask out, so
    # slot reuse needs no zeroing (same invariant as the dense pool).
    valid = (jnp.arange(t_max)[None, None, :]
             <= q_pos[:, :, None])[:, None]
    scores = jnp.where(valid, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32),
                           axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhts,bshd->bthd", probs, cv).reshape(S, T, width)
    x = x + tp.row_parallel_dense(ctx, p["wo"], axis)
    m = tp.tp_mlp(_ln(x, *p["ln2"]), p["w1"], p["w2"], axis,
                  act=jax.nn.gelu)
    return x + m, (ck, cv)


def _logits(x_last, params, axis):
    """[B, D] -> [B, V]: column-parallel head, vocab slices re-joined by
    one tiled all_gather (axis-order concatenation matches the
    column-sharded placement of :func:`shard_tp_lm`)."""
    ll = x_last @ params["head"]
    return lax.all_gather(ll, axis, axis=-1, tiled=True)


def _tp_generate_body(params, prompt, temperature, rng, *, axis,
                      num_heads, steps, top_k, top_p, eos_id):
    """The shard_map body: semantics mirror ``generate._generate_scan``
    (prefill fills caches in one causal pass; ``lax.scan`` decode; EOS
    rows freeze to ``eos_id``)."""
    B, Tp = prompt.shape
    t_max = Tp + steps

    def sample(logits, rng):
        return _sample(logits, rng, temperature, top_k, top_p,
                       prompt.dtype)

    x = params["embed"][prompt]              # [B, Tp, D] replicated
    caches = []
    for p in params["blocks"]:
        x, cache = _block_prefill(x, p, axis, num_heads, t_max)
        caches.append(cache)
    x_last = _ln(x[:, -1], *params["ln_f"])
    rng, sub = jax.random.split(rng)
    first = sample(_logits(x_last, params, axis), sub)

    if steps == 1:
        return jnp.concatenate([prompt, first[:, None]], axis=1)

    done0 = (first == eos_id) if eos_id is not None else \
        jnp.zeros((B,), bool)

    def step(carry, i):
        caches, tok_in, rng, done = carry
        x = params["embed"][tok_in[:, None]]
        new_caches = []
        for p, cache in zip(params["blocks"], caches):
            x, cache = _block_decode(x, p, cache, i, axis, num_heads)
            new_caches.append(cache)
        x_last = _ln(x[:, 0], *params["ln_f"])
        rng, sub = jax.random.split(rng)
        nxt = sample(_logits(x_last, params, axis), sub)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
            done = done | (nxt == eos_id)
        return (new_caches, nxt, rng, done), nxt

    init = (caches, first, rng, done0)
    _, toks = lax.scan(step, init,
                       Tp + jnp.arange(steps - 1, dtype=jnp.int32))
    return jnp.concatenate([prompt, first[:, None], toks.T], axis=1)


def _tp_beam_body(params, prompt, *, axis, num_heads, steps, K, eos_id,
                  length_penalty):
    """Beam search over the TP stack: prefill on B rows, tile the
    head-local caches to B*K beam rows, decode with the SAME trellis
    bookkeeping as the dense beam (``generate._beam_expand`` /
    ``_beam_backtrack``).  The parent-gather cache reindex is a local
    batch-dim gather on every device — beam rows are replicated, only
    heads are sharded — so TP adds no collective beyond the per-token
    psum/all_gather the greedy path already pays."""
    B, Tp = prompt.shape
    t_max = Tp + steps
    x = params["embed"][prompt]
    caches = []
    for p in params["blocks"]:
        x, cache = _block_prefill(x, p, axis, num_heads, t_max)
        caches.append(cache)
    lp0 = jax.nn.log_softmax(
        _logits(_ln(x[:, -1], *params["ln_f"]), params,
                axis).astype(jnp.float32), -1)
    V = lp0.shape[-1]
    top_lp, top_tok = lax.top_k(lp0, K)          # [B, K]
    top_tok = top_tok.astype(prompt.dtype)
    caches = jax.tree.map(lambda c: jnp.repeat(c, K, axis=0), caches)

    if steps == 1:
        best = top_tok[:, 0]
        return jnp.concatenate([prompt, best[:, None]], axis=1)

    fin0 = (top_tok == eos_id) if eos_id is not None else \
        jnp.zeros((B, K), bool)
    len0 = jnp.ones((B, K), jnp.int32)

    def step(carry, i):
        caches, lp, tok, fin, ln = carry
        x = params["embed"][tok.reshape(B * K, 1)]
        new_caches = []
        for p, cache in zip(params["blocks"], caches):
            x, cache = _block_decode(x, p, cache, i, axis, num_heads)
            new_caches.append(cache)
        logits = _logits(_ln(x[:, 0], *params["ln_f"]), params, axis)
        step_lp = jax.nn.log_softmax(
            logits.astype(jnp.float32), -1).reshape(B, K, V)
        new_lp, new_tok, new_fin, new_ln, parent = _beam_expand(
            lp, fin, ln, step_lp, eos_id, prompt.dtype)
        reorder = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
        new_caches = jax.tree.map(lambda c: c[reorder], new_caches)
        return (new_caches, new_lp, new_tok, new_fin, new_ln), \
            (new_tok, parent)

    (_, final_lp, _, _, final_len), (toks, parents) = lax.scan(
        step, (caches, top_lp, top_tok, fin0, len0),
        Tp + jnp.arange(steps - 1, dtype=jnp.int32))

    return _beam_backtrack(prompt, top_tok, toks, parents, final_lp,
                           final_len, length_penalty)


@lru_cache(maxsize=None)
def _tp_beam_fn(mesh, axis, num_heads, steps, depth, beams, eos_id,
                length_penalty):
    from jax.sharding import PartitionSpec as P

    body = partial(_tp_beam_body, axis=axis, num_heads=num_heads,
                   steps=steps, K=beams, eos_id=eos_id,
                   length_penalty=length_penalty)
    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(_tp_specs(depth, axis), P()),
        out_specs=P(), check_vma=False))


def tp_beam_search(params, prompt, steps: int, *, mesh, axis,
                   num_heads: int, beams: int,
                   eos_id: Optional[int] = None,
                   length_penalty: float = 0.0,
                   sharded: Optional[Tuple] = None) -> jax.Array:
    """Beam search on the tensor-parallel stack — semantics identical
    to :func:`.generate.beam_search` (cumulative log-prob, finished
    beams freeze at zero added score on ``eos_id``, final ranking by
    ``logprob / len**length_penalty``), with weights and KV caches
    sharded 1/n over the model axis."""
    prompt = jnp.asarray(prompt)
    if prompt.ndim != 2:
        raise ValueError(f"prompt must be [batch, time], got "
                         f"{prompt.shape}")
    if steps <= 0:
        return prompt
    if beams < 1:
        raise ValueError(f"beams must be >= 1, got {beams}")
    vocab = params["embed"].shape[0]
    if beams > vocab:
        raise ValueError(f"beams {beams} exceeds vocab {vocab}")
    placed, _ = sharded if sharded is not None else \
        shard_tp_lm(params, mesh, axis)
    fn = _tp_beam_fn(mesh, axis, num_heads, steps,
                     len(params["blocks"]), int(beams),
                     None if eos_id is None else int(eos_id),
                     float(length_penalty))
    return fn(placed, prompt)


@lru_cache(maxsize=None)
def _tp_fn(mesh, axis, num_heads, steps, depth, top_k, top_p, eos_id):
    """Build (once per static config — jit itself respecializes per
    prompt shape) the jitted shard_map decode fn; same caching idiom as
    ``generate._parallel_fn``.

    Unbounded by design (ADVICE r4, consistency-accepted): each distinct
    (mesh, steps, sampling) tuple retains its compiled executable and
    mesh reference forever.  A long-lived server that varies ``steps``
    freely should quantize it to buckets (e.g. round up to a multiple of
    64 and truncate the output) or call :func:`clear_serving_caches`
    between shape regimes."""
    from jax.sharding import PartitionSpec as P

    body = partial(_tp_generate_body, axis=axis, num_heads=num_heads,
                   steps=steps, top_k=top_k, top_p=top_p, eos_id=eos_id)
    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(_tp_specs(depth, axis), P(), P(), P()),
        out_specs=P(), check_vma=False))


# ---------------------------------------------------------------------------
# Slot-pooled TP primitives — the tensor-parallel mirror of the
# ``generate.slot_prefill`` / ``slot_decode_step`` / ``slot_verify_step``
# trio, so a Router replica can be a whole TP mesh slice
# (serving/tp_engine.py) instead of one device.  The pool cache is a
# list (one per block) of head-local ``(k, v)`` pairs
# ``[S, t_max, H, dh]`` sharded ``P(None, None, axis, None)``: slots
# replicate, heads shard 1/n, so KV memory scales with the axis exactly
# like static TP decode.  Admission reuses the dense
# ``generate.slot_write`` — a batch-dim dynamic_update_slice GSPMD
# keeps local.  Sampling flows through the SAME ``_sample_rows`` /
# ``_sample_keys`` as the dense pool (replicated math inside shard_map,
# identical keys), which is what makes a dense replica and a TP replica
# emit bitwise-identical streams for the same (seed, prompt).
# ---------------------------------------------------------------------------


def _tp_slot_prefill_body(params, prompt, true_len, seeds, idxs, temps,
                          top_ks, top_ps, *, axis, num_heads, t_max):
    x = params["embed"][prompt]                  # [1, Tp, D] replicated
    caches = []
    for p in params["blocks"]:
        x, cache = _block_prefill(x, p, axis, num_heads, t_max)
        caches.append(cache)
    # Slice at the TRUE last position (bucketed prefill right-pads the
    # prompt; causality keeps real positions bitwise independent of the
    # padding — see generate.slot_prefill).
    x_true = lax.dynamic_slice_in_dim(
        x, clamp_slot_positions(true_len - 1, x.shape[1]), 1,
        axis=1)[:, 0]
    first = _sample_rows(
        _logits(_ln(x_true, *params["ln_f"]), params, axis),
        _sample_keys(seeds, idxs), temps, top_ks, top_ps, prompt.dtype)
    return caches, first


def _tp_slot_step_body(params, caches, tokens, positions, seeds, idxs,
                       temps, top_ks, top_ps, *, axis, num_heads):
    S, T = tokens.shape
    x = params["embed"][tokens]
    new_caches = []
    for p, cache in zip(params["blocks"], caches):
        x, cache = _block_decode_rows(x, p, cache, positions, axis,
                                      num_heads)
        new_caches.append(cache)
    logits = _logits(_ln(x, *params["ln_f"]).reshape(S * T, -1),
                     params, axis)
    # Position j of row s keys on idx_s + j — the verify-step key
    # schedule (generate.slot_verify_step); T == 1 degenerates to the
    # plain per-token key.
    keys = _sample_keys(
        jnp.repeat(seeds, T),
        (idxs[:, None] + jnp.arange(T, dtype=jnp.int32)).reshape(-1))
    flat = _sample_rows(logits, keys, jnp.repeat(temps, T),
                        jnp.repeat(top_ks, T), jnp.repeat(top_ps, T),
                        tokens.dtype)
    return new_caches, flat.reshape(S, T)


def _tp_cache_specs(depth, axis):
    from jax.sharding import PartitionSpec as P

    return [(P(None, None, axis, None),) * 2 for _ in range(depth)]


@lru_cache(maxsize=None)
def _tp_slot_prefill_fn(mesh, axis, num_heads, depth, t_max):
    from jax.sharding import PartitionSpec as P

    body = partial(_tp_slot_prefill_body, axis=axis,
                   num_heads=num_heads, t_max=t_max)
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(_tp_specs(depth, axis),) + (P(),) * 7,
        out_specs=(_tp_cache_specs(depth, axis), P()),
        check_vma=False))


@lru_cache(maxsize=None)
def _tp_slot_step_fn(mesh, axis, num_heads, depth):
    from jax.sharding import PartitionSpec as P

    body = partial(_tp_slot_step_body, axis=axis, num_heads=num_heads)
    cs = _tp_cache_specs(depth, axis)
    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(_tp_specs(depth, axis), cs) +
        (P(),) * 7,
        out_specs=(cs, P()), check_vma=False))


def tp_slot_prefill(params, prompt, *, mesh, axis, num_heads, t_max,
                    true_len=None, sampling=None):
    """Prefill one request on a fresh head-local cache padded to
    ``t_max`` (the slot block).  ``params`` must already be placed by
    :func:`shard_tp_lm` on ``mesh``.  Returns ``(cache, first [1])`` —
    cache is the per-block list of sharded ``(k, v)`` pairs ready for
    ``generate.slot_write`` into the pool."""
    prompt = jnp.asarray(prompt)
    if true_len is None:
        true_len = prompt.shape[1]
    if sampling is None:
        sampling = _greedy_sampling(prompt.shape[0])
    fn = _tp_slot_prefill_fn(mesh, axis, num_heads,
                             len(params["blocks"]), int(t_max))
    return fn(params, prompt, jnp.asarray(true_len, jnp.int32),
              *sampling)


def tp_slot_decode(params, cache, tokens, positions, *, mesh, axis,
                   num_heads, sampling=None):
    """One pooled decode/verify forward over the TP mesh: ``tokens``
    [S, T] (T = 1 for the continuous-batching tick, K+1 for the
    speculative verify), ``positions`` [S] per-slot write depths.
    Returns ``(new_cache, samples [S, T])`` — one compiled executable
    per T serves the whole trace."""
    tokens = jnp.asarray(tokens)
    if sampling is None:
        sampling = _greedy_sampling(tokens.shape[0])
    fn = _tp_slot_step_fn(mesh, axis, num_heads, len(params["blocks"]))
    return fn(params, cache, tokens, jnp.asarray(positions), *sampling)


def clear_serving_caches():
    """Drop every cached compiled serving executable across the serving
    modules (``_tp_fn``/``_tp_beam_fn`` here, ``pp_generate._pp_fn``,
    ``generate._parallel_fn``/``_beam_parallel_fn``).  The factory
    caches are keyed on (mesh, steps, sampling config, ...) and
    unbounded (see :func:`_tp_fn`); long-lived servers that cycle
    through many step counts or sampling configs can call this between
    shape regimes to release executables and mesh references."""
    import importlib

    # Module-path imports: the package re-exports same-named FUNCTIONS
    # (`models.generate` is the function), so `from . import generate`
    # would bind the function, not the module (the round-4 shadowing
    # class).
    _g = importlib.import_module(__package__ + ".generate")
    _pp = importlib.import_module(__package__ + ".pp_generate")

    _tp_fn.cache_clear()
    _tp_beam_fn.cache_clear()
    _tp_slot_prefill_fn.cache_clear()
    _tp_slot_step_fn.cache_clear()
    _pp._pp_fn.cache_clear()
    _g._parallel_fn.cache_clear()
    _g._beam_parallel_fn.cache_clear()


def tp_generate(params, prompt, steps: int, *, mesh, axis,
                num_heads: int, temperature: float = 0.0,
                top_k: Optional[int] = None, top_p: Optional[float] = None,
                eos_id: Optional[int] = None,
                rng: Optional[jax.Array] = None,
                sharded: Optional[Tuple] = None) -> jax.Array:
    """Tensor-parallel generation over ``mesh``'s ``axis``.

    ``params`` is a full tree from :func:`init_tp_lm` (sharded here via
    :func:`shard_tp_lm`), or pass ``sharded=(placed, specs)`` to reuse a
    placement across calls.  Returns the replicated
    ``[B, Tp + steps]`` token matrix; greedy at ``temperature=0``,
    else categorical with optional top-k/top-p filtering, EOS-frozen
    rows padded with ``eos_id`` — identical semantics to
    :func:`.generate.generate`."""
    prompt = jnp.asarray(prompt)
    if prompt.ndim != 2:
        raise ValueError(f"prompt must be [batch, time], got "
                         f"{prompt.shape}")
    if steps <= 0:
        return prompt
    _check_sampling(top_k, top_p)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    placed, _ = sharded if sharded is not None else \
        shard_tp_lm(params, mesh, axis)
    fn = _tp_fn(mesh, axis, num_heads, steps, len(params["blocks"]),
                top_k, top_p, None if eos_id is None else int(eos_id))
    return fn(placed, prompt, jnp.float32(temperature), rng)

"""Shared dense oracle for the TP/PP/continuous-serving paths:
single-device, cache-free greedy decode of the init_tp_lm architecture
(recomputes the full forward every step, so a KV-cache bug cannot hide
in both sides).

Importable home (ISSUE 9 satellite): this used to live at
``tests/_tp_oracle.py`` and ``examples/parallel_serving.py`` reached it
through a ``sys.path.insert`` hack; now the tests, the examples, and
the graft-entry smoke all import ONE copy as
``torchmpi_tpu.models.oracle``.  The math stays deliberately
independent of the serving implementations it oracles (its own
layernorm, no KV cache, numpy-side loop)."""

import jax
import jax.numpy as jnp
import numpy as np

from .tp_generate import init_tp_lm
from .transformer import apply_rope


def _ln(h, scale, bias):
    mu = h.mean(-1, keepdims=True)
    var = ((h - mu) ** 2).mean(-1, keepdims=True)
    return (h - mu) / np.sqrt(var + 1e-6) * scale + bias


def dense_forward(params, toks, num_heads):
    """Full-sequence forward on the unsharded tree: returns last-position
    logits [B, V]."""
    x = params["embed"][toks]
    B, T, D = x.shape
    for p in params["blocks"]:
        h = _ln(x, *p["ln1"])
        width = p["wq"].shape[-1]
        dh = width // num_heads
        pos = jnp.arange(T, dtype=jnp.int32)
        q = apply_rope((h @ p["wq"]).reshape(B, T, num_heads, dh), pos)
        k = apply_rope((h @ p["wk"]).reshape(B, T, num_heads, dh), pos)
        v = (h @ p["wv"]).reshape(B, T, num_heads, dh)
        s = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(dh)
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool)), s,
                      jnp.finfo(s.dtype).min)
        probs = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
        ctx = jnp.einsum("bhts,bshd->bthd", probs.astype(x.dtype),
                         v).reshape(B, T, width)
        x = x + ctx @ p["wo"]
        h2 = _ln(x, *p["ln2"])
        x = x + jax.nn.gelu(h2 @ p["w1"]) @ p["w2"]
    return _ln(x[:, -1], *params["ln_f"]) @ params["head"]


def dense_greedy(params, prompt, steps, num_heads, eos_id=None):
    toks = jnp.asarray(prompt)
    done = np.zeros(toks.shape[0], bool)
    for _ in range(steps):
        logits = dense_forward(params, toks, num_heads)
        nxt = np.asarray(jnp.argmax(logits, axis=-1)).astype(
            np.asarray(prompt).dtype)
        if eos_id is not None:
            nxt = np.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        toks = jnp.concatenate([toks, jnp.asarray(nxt)[:, None]], axis=1)
    return np.asarray(toks)


def seq_logprob(params, toks, num_heads, prompt_len):
    """Sum of log p(tok_i | prefix) over the generated positions — the
    brute-force beam-scoring oracle.  Caveat: every position is scored
    at its TRUE model probability, including eos repeats after a first
    eos, whereas an eos-stopped beam freezes finished hypotheses at 0
    added log-prob — so only compare against beams run WITHOUT
    eos_id."""
    toks = np.asarray(toks)
    B, total = toks.shape
    lp = np.zeros(B)
    for i in range(prompt_len, total):
        logits = dense_forward(params, jnp.asarray(toks[:, :i]),
                               num_heads)
        logp = np.asarray(jax.nn.log_softmax(
            logits.astype(jnp.float32), axis=-1))
        lp += logp[np.arange(B), toks[:, i]]
    return lp


def setup(seed=0, vocab=64, embed=32, depth=2, num_heads=8, B=2, Tp=4):
    params = init_tp_lm(jax.random.PRNGKey(seed), vocab=vocab,
                        embed=embed, depth=depth, num_heads=num_heads)
    prompt = np.random.RandomState(seed + 1).randint(
        0, vocab, size=(B, Tp)).astype(np.int32)
    return params, prompt

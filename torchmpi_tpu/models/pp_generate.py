"""Pipeline-parallel serving: round-robin micro-group decode over a
stage axis.

VERDICT r3 missing #5's other half: serving existed for dense/EP/
Ulysses (and now TP, :mod:`.tp_generate`) but not PP.  Here the model's
layers split into S contiguous stages over the mesh axis (each device
holds 1/S of the weights AND 1/S of the KV cache — the PP serving case
is models whose weights exceed one chip but whose per-token latency
budget tolerates S hops), and the batch splits into S micro-groups that
rotate through the stages:

    tick t, stage s: process micro-group (t - s) mod S at token k =
    (t - s) // S.

At steady state every stage works on a different micro-group's current
token each tick — the autoregressive dependency (token k+1 needs token
k through ALL stages) is hidden by round-robin batch interleaving, the
standard PP decode schedule.  One wraparound ppermute per tick carries
(activation, sampled-token) pairs: stage s's activation to s+1, and the
last stage's sampled token back to stage 0, which embeds it exactly one
tick later — the schedule's return hop lands on the group's next
stage-0 slot with zero idle ticks.

Teacher-forced prefill uses the SAME loop (stage 0 reads prompt[g, k]
while k < Tp, the sampled return token after), so prefill+decode is one
``lax.scan`` of ``S * (Tp + steps)`` ticks whose body appears once in
the HLO (the weak-#6 rule: schedules scan, never unroll).

Same parameter layout as :func:`.tp_generate.init_tp_lm` (per-block
ln1/ln2, wq/wk/wv/wo, w1/w2 + embed/ln_f/head) — one checkpoint tree
serves dense, TP and PP decode.  Beam search is deliberately NOT
offered on PP: the beam parent-gather would have to reindex cache rows
for a micro-group whose K beam rows live at different pipeline depths
mid-flight, serializing the round-robin schedule to one group per S
ticks — at which point TP beam (:func:`.tp_generate.tp_beam_search`,
local-gather reindex, no schedule coupling) strictly dominates; use it
when beams are needed on a sharded model.  Sampling semantics (greedy /
temperature / top-k / top-p via ``generate._filter_logits``, EOS
freeze) mirror ``_generate_scan`` — but note that only GREEDY
(temperature=0) output is token-identical across dense/TP/PP: at
temperature>0 this schedule draws from a ``fold_in(rng, group, k)``
stream while the dense/TP paths split one key sequentially, so sampled
streams are deterministic per path, not shared across paths (ADVICE
r4).  The reference has no serving at all (SURVEY.md §1);
beyond-reference surface on the §6.7 mesh guarantee.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .generate import _check_sampling, _sample
from .tp_generate import _ln
from .transformer import apply_rope


def _axes_tuple(axis):
    return axis if isinstance(axis, tuple) else (axis,)


def _stack_blocks(blocks):
    """[L] list of per-layer dicts -> one tree with leading layer dim,
    shardable over the stage axis with a single P(axis) leading spec."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)


def _layer_decode(x, p, cache, rows, pos, *, valid=None):
    """One decode layer for one micro-group's current token.

    x: [Bg, D]; cache: (k, v) [B, Tmax, H, dh] (full batch, this
    layer's); rows: traced start row of the group's cache slice; pos:
    traced token position.  ``valid=False`` turns the cache write into
    a no-op by re-writing the existing row (one-row cost — never a
    full-cache select).  Returns (x, cache)."""
    ck, cv = cache
    Bg, D = x.shape
    _, t_max, H, dh = ck.shape
    h = _ln(x, *p["ln1"])
    q = (h @ p["wq"]).reshape(Bg, H, dh)
    k1 = (h @ p["wk"]).reshape(Bg, H, dh)
    v1 = (h @ p["wv"]).reshape(Bg, H, dh)
    posv = pos[None].astype(jnp.int32)
    q = apply_rope(q[:, None], posv)[:, 0]
    k1, v1 = k1[:, None], v1[:, None]
    k1 = apply_rope(k1, posv)
    if valid is not None:
        old_k = lax.dynamic_slice(ck, (rows, pos, 0, 0), (Bg, 1, H, dh))
        old_v = lax.dynamic_slice(cv, (rows, pos, 0, 0), (Bg, 1, H, dh))
        k1 = jnp.where(valid, k1, old_k)
        v1 = jnp.where(valid, v1, old_v)
    ck = lax.dynamic_update_slice(ck, k1, (rows, pos, 0, 0))
    cv = lax.dynamic_update_slice(cv, v1, (rows, pos, 0, 0))
    ck_g = lax.dynamic_slice(ck, (rows, 0, 0, 0), (Bg, t_max, H, dh))
    cv_g = lax.dynamic_slice(cv, (rows, 0, 0, 0), (Bg, t_max, H, dh))
    s = jnp.einsum("bhd,bshd->bhs", q, ck_g) / np.sqrt(dh)
    s = jnp.where((jnp.arange(t_max) <= pos)[None, None, :], s,
                  jnp.finfo(s.dtype).min)
    probs = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhs,bshd->bhd", probs, cv_g).reshape(Bg, H * dh)
    x = x + ctx @ p["wo"]
    h2 = _ln(x, *p["ln2"])
    x = x + jax.nn.gelu(h2 @ p["w1"]) @ p["w2"]
    return x, (ck, cv)


def _pp_generate_body(blocks_local, aux, prompt, temperature, rng, *,
                      axis, steps, layers_per_stage, num_heads, top_k,
                      top_p, eos_id):
    """The shard_map body.  blocks_local: stacked [L/S, ...] tree (this
    stage's layers); aux: dict(embed, ln_f, head) replicated; prompt:
    [B, Tp] replicated."""
    axes = _axes_tuple(axis)
    S = 1
    for a in axes:
        S *= lax.axis_size(a)
    s_idx = lax.axis_index(axes)
    B, Tp = prompt.shape
    if B % S:
        raise ValueError(f"batch {B} must divide by the stage-axis "
                         f"size {S}")
    Bg = B // S
    D = aux["embed"].shape[1]
    V = aux["head"].shape[1]
    t_max = Tp + steps
    is_first = s_idx == 0
    is_last = s_idx == S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]

    def sample(logits, rng):
        return _sample(logits, rng, temperature, top_k, top_p,
                       prompt.dtype)

    # KV caches: one (k, v) pair per LOCAL layer, allocated over the
    # FULL batch so any micro-group can slice its own rows (cache
    # memory still 1/S per device: only this stage's layers live here).
    # Allocated in the COMPUTE dtype, like tp_generate's prefill-built
    # caches (ADVICE r4): a bf16 tree must run bf16 on PP too — both for
    # the dense == TP == PP guarantee and for the cache footprint.  The
    # compute dtype is the embed/weight promotion (a mixed tree, e.g.
    # bf16 embed + fp32 blocks, promotes activations at the first
    # matmul, and the cache rows hold those promoted k/v).
    H = num_heads
    dh = blocks_local["wq"].shape[-1] // H
    cdtype = jnp.result_type(aux["embed"].dtype,
                             blocks_local["wq"].dtype)
    caches = [
        (jnp.zeros((B, t_max, H, dh), cdtype),
         jnp.zeros((B, t_max, H, dh), cdtype))
        for _ in range(layers_per_stage)
    ]

    outbuf = jnp.zeros((B, steps), prompt.dtype)
    done = jnp.zeros((B,), bool)
    x0 = jnp.zeros((Bg, D), cdtype)
    tok0 = jnp.zeros((Bg,), prompt.dtype)

    n_ticks = S * (Tp + steps)

    def tick(carry, t):
        caches, outbuf, done, x_in, tok_in = carry
        g = jnp.mod(t - s_idx, S)
        k = (t - s_idx) // S
        valid = (t >= s_idx) & (k <= Tp + steps - 2)
        rows = g * Bg

        # Stage 0 input: teacher-forced prompt token while k < Tp, else
        # the sampled token that just arrived from the last stage.
        prom_g = lax.dynamic_slice(prompt, (rows, jnp.clip(k, 0, Tp - 1)),
                                   (Bg, 1))[:, 0]
        tok = jnp.where(k < Tp, prom_g, tok_in)
        x = jnp.where(is_first, aux["embed"][tok].astype(cdtype), x_in)

        new_caches = []
        for li in range(layers_per_stage):
            p_li = jax.tree.map(lambda a, li=li: a[li], blocks_local)
            y, cache = _layer_decode(x, p_li, caches[li], rows,
                                     jnp.clip(k, 0, t_max - 1),
                                     valid=valid)
            # Masked ticks must not corrupt the activation (cache rows
            # are masked inside _layer_decode at one-row cost).
            x = jnp.where(valid, y, x)
            new_caches.append(cache)

        # Last stage: sample position k+1's token, record it, freeze
        # finished rows.
        x_last = _ln(x, *aux["ln_f"])
        logits = x_last @ aux["head"]
        rng_gk = jax.random.fold_in(jax.random.fold_in(rng, g), k)
        nxt = sample(logits, rng_gk)
        done_g = lax.dynamic_slice(done, (rows,), (Bg,))
        if eos_id is not None:
            nxt = jnp.where(done_g, jnp.asarray(eos_id, nxt.dtype), nxt)
            done_g = done_g | (nxt == eos_id)
        # emit guards BOTH the token record and the done update: during
        # teacher-forced prefill (k+1 < Tp) nxt is a discarded
        # prediction for a prompt position — letting it flip done would
        # freeze the row before generation starts.
        emit = valid & is_last & (k + 1 >= Tp)
        col = jnp.clip(k + 1 - Tp, 0, steps - 1)
        upd = lax.dynamic_update_slice(outbuf, nxt[:, None], (rows, col))
        outbuf = jnp.where(emit, upd, outbuf)
        done = jnp.where(emit,
                         lax.dynamic_update_slice(done, done_g, (rows,)),
                         done)

        send = (jnp.where(valid, x, x_in),
                jnp.where(valid & is_last, nxt, tok_in))
        x_nxt, tok_nxt = lax.ppermute(send, axes, perm)
        return (new_caches, outbuf, done, x_nxt, tok_nxt), None

    (caches, outbuf, done, _, _), _ = lax.scan(
        tick, (caches, outbuf, done, x0, tok0),
        jnp.arange(n_ticks, dtype=jnp.int32))
    # Only the last stage's buffer holds real tokens; replicate it.
    outbuf = lax.psum(jnp.where(is_last, outbuf, 0), axes)
    return jnp.concatenate([prompt, outbuf], axis=1)


@lru_cache(maxsize=None)
def _pp_fn(mesh, axis, steps, layers_per_stage, num_heads, top_k, top_p,
           eos_id):
    from jax.sharding import PartitionSpec as P

    body = partial(_pp_generate_body, axis=axis, steps=steps,
                   layers_per_stage=layers_per_stage,
                   num_heads=num_heads, top_k=top_k, top_p=top_p,
                   eos_id=eos_id)
    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(axis), P(), P(), P(), P()),
        out_specs=P(), check_vma=False))


def shard_pp_lm(params, mesh, axis):
    """Stack the per-layer blocks into one [L, ...] tree and place it
    over ``axis`` (each device materializes only its stage's layers);
    embed/ln_f/head stay replicated.  Returns ``(stacked, aux)`` for
    reuse across :func:`pp_generate` calls via ``sharded=`` — a serving
    loop should pay the weight transfer once, not per call."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    stacked = jax.device_put(_stack_blocks(params["blocks"]),
                             NamedSharding(mesh, P(axis)))
    aux = {"embed": params["embed"], "ln_f": params["ln_f"],
           "head": params["head"]}
    return stacked, aux


def pp_generate(params, prompt, steps: int, *, mesh, axis,
                num_heads: int, temperature: float = 0.0,
                top_k: Optional[int] = None,
                top_p: Optional[float] = None,
                eos_id: Optional[int] = None,
                rng: Optional[jax.Array] = None,
                sharded=None) -> jax.Array:
    """Pipeline-parallel generation over ``mesh``'s ``axis``.

    ``params``: a full tree in the :func:`.tp_generate.init_tp_lm`
    layout (or pass ``sharded=shard_pp_lm(...)`` to reuse a placement
    across calls); ``depth`` must divide by the stage count and the
    batch by the stage count (micro-groups).  Returns the replicated
    ``[B, Tp + steps]`` tokens with the same sampling/EOS semantics as
    :func:`.generate.generate`."""
    prompt = jnp.asarray(prompt)
    if prompt.ndim != 2:
        raise ValueError(f"prompt must be [batch, time], got "
                         f"{prompt.shape}")
    if steps <= 0:
        return prompt
    _check_sampling(top_k, top_p)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    S = 1
    for a in _axes_tuple(axis):
        S *= mesh.shape[a]
    depth = len(params["blocks"])
    if depth % S:
        raise ValueError(f"depth {depth} must divide by the stage-axis "
                         f"size {S}")
    if prompt.shape[0] % S:
        raise ValueError(f"batch {prompt.shape[0]} must divide by the "
                         f"stage-axis size {S} (micro-groups)")
    stacked, aux = sharded if sharded is not None else \
        shard_pp_lm(params, mesh, axis)
    fn = _pp_fn(mesh, axis, steps, depth // S, num_heads, top_k, top_p,
                None if eos_id is None else int(eos_id))
    return fn(stacked, aux, prompt, jnp.float32(temperature), rng)

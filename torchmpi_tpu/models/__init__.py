"""Model zoo for the reference's workloads (SURVEY.md §8.1): LeNet (MNIST),
ResNet-20 (CIFAR-10), ResNet-50 (ImageNet), AlexNet (Downpour).  Implemented
in flax.linen, bfloat16-friendly, static shapes — MXU-ready."""

from .lenet import LeNet  # noqa: F401
from .alexnet import AlexNet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet20,
    ResNet50,
    ResNet101,
    ResNet152,
    BasicBlock,
    BottleneckBlock,
)
from .transformer import TransformerLM  # noqa: F401
from .generate import (  # noqa: F401
    beam_search,
    beam_search_parallel,
    generate,
    generate_parallel,
)
from .tp_generate import (  # noqa: F401
    init_tp_lm,
    shard_tp_lm,
    tp_beam_search,
    tp_generate,
)
from .pp_generate import pp_generate, shard_pp_lm  # noqa: F401

"""Model zoo for the reference's workloads (SURVEY.md §8.1): LeNet (MNIST),
ResNet-20 (CIFAR-10), ResNet-50 (ImageNet), AlexNet (Downpour).  Implemented
in flax.linen, bfloat16-friendly, static shapes — MXU-ready."""

from .lenet import LeNet  # noqa: F401

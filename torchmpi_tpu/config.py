"""Runtime configuration for torchmpi_tpu.

The reference exposed three knob mechanisms (SURVEY.md §6.6, reconstructed from
facebookarchive/TorchMPI — reference mount empty, see SURVEY.md §0): arguments to
``mpi.start``, C-level setters (``torchmpi_set_{flat,hierarchical}_collectives``,
``torchmpi_set_{staged,direct}_collectives``, chunk-size setters), and the Lua
``collectiveSelector`` table.  Here all of that collapses into one dataclass plus
environment-variable overrides, while keeping the reference's key property that
implementations are *runtime-switchable* (benchmarks compare them).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v is not None else default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v is not None else default


def _env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


@dataclasses.dataclass
class Config:
    """All runtime knobs.

    Attributes mirror the reference's setters:

    - ``hierarchical``  <-> torchmpi_set_{flat,hierarchical}_collectives
    - ``backend``       <-> mpi.collectiveSelector ({mpi,nccl,gloo,p2p} ->
                            {"xla","hierarchical","pallas"})
    - ``chunk_bytes``   <-> torchmpi_set_*_buffer_size / chunk setters; used by the
                            chunked Pallas ring collective and PS staging.
    """

    # --- topology -----------------------------------------------------------
    # Number of devices along the inner (ICI, intra-slice) mesh axis.  None =
    # auto: local device count for a single process; all devices for one slice.
    ici_size: Optional[int] = None
    # Number of slices / outer (DCN) axis.  None = auto (process count // hosts
    # per slice, or 1).
    dcn_size: Optional[int] = None
    # First-class N-D world mesh (VERDICT r3 #6; SURVEY.md §6.7: the mesh
    # design must not hard-code axes): ordered dict of axis-name -> size,
    # e.g. {"pp": 2, "tp": 2, "dp": 2}.  Built as ONE mesh at init with
    # those named axes — no communicator pushes needed for N-D
    # parallelism; push_communicator remains the split/subset API on
    # top.  Dict order is major -> minor: the LAST axis varies fastest
    # over the raw device order, i.e. is the most interconnect-local —
    # put tensor-parallel innermost, data/pipeline outermost.  At most
    # one size may be -1 (inferred from the device count).  Mutually
    # exclusive with ici_size/dcn_size (which build the classic 2-level
    # (dcn, ici) world).  Env: TORCHMPI_TPU_MESH_SHAPE="pp=2,tp=2,dp=-1".
    mesh_shape: Optional[dict] = None
    # Use GPU/TPU devices if available (mirrors mpi.start(withCuda)).
    use_accelerator: bool = True

    # --- collective implementation selection -------------------------------
    # Default backend for collectives: "xla" (stock, = reference's mpi/nccl
    # path), "hierarchical" (2-level ICI+DCN, = reference's custom
    # hierarchical path), "pallas" (chunked ring kernels, = reference's custom
    # chunked/pipelined path), or "auto" (measured online per (op, size
    # bucket, mesh, platform) and persisted in the tuning plan DB — see
    # torchmpi_tpu/tuning/ and docs/TUNING.md).
    backend: str = "xla"
    # Path of the persistent tuning-plan JSON consulted/extended by
    # backend="auto" (and loadable from benchmarks/autotune.py --plan-out).
    # None resolves to TORCHMPI_TPU_TUNING_PLAN, then the repo-local
    # default (tuning.DEFAULT_PLAN_PATH).  Corrupt/mismatched files
    # degrade silently to static selection; they never crash a job.
    tuning_plan_path: Optional[str] = None
    # Fenced timing rounds per candidate for the online measurement (the
    # median is scored; the noise gate needs >= 3 rounds to be
    # meaningful — same discipline as benchmarks/autotune.py).
    tuning_rounds: int = 3
    # Per-op overrides of `backend` (reference: the collectiveSelector table
    # chose an implementation per collective class).  e.g.
    # {"allreduce": "pallas", "broadcast": "xla"}.
    backend_per_op: Optional[dict] = None
    # Flat vs hierarchical collectives (reference: torchmpi_set_flat/
    # hierarchical_collectives).  When True, allreduce over a 2-level mesh is
    # staged: reduce_scatter(ici) -> allreduce(dcn) -> all_gather(ici).
    hierarchical: bool = False
    # Subchunk size in bytes for the chunked/pipelined pallas ring allreduce:
    # when a tensor's per-ring-chunk payload (size/n) exceeds this, the ring
    # streams ~chunk_bytes subchunks HBM->VMEM with the next subchunk's RDMA
    # in flight, keeping VMEM residency at ~4*chunk_bytes (2 comm + 2
    # accumulate slots) however large the tensor.  Smaller tensors use the
    # VMEM-resident kernels.  Changing it via set_config invalidates cached
    # executables, so the new schedule takes effect immediately.
    chunk_bytes: int = 4 * 1024 * 1024
    # Tensors smaller than this stay on the stock path even when a custom
    # backend is selected (the reference had size cutover constants).
    custom_min_bytes: int = 64 * 1024
    # Bidirectional pallas ring allreduce: halves rotate in opposite
    # directions concurrently (2x bandwidth bound on full-duplex ICI).
    pallas_bidirectional: bool = False
    # Staged vs direct collectives (reference: torchmpi_set_staged/
    # direct_collectives — GPU tensors staged through pinned host buffers
    # when MPI was not CUDA-aware, SURVEY.md §6.6/§3 C5).  TPU mapping:
    # when True, the EAGER tensor verbs round-trip through host memory
    # and reduce on the host CPU (devices -> host -> devices), the same
    # data path the reference's staged mode took.  In-axis collectives
    # (inside jit/shard_map) are always direct — the device fabric is
    # "CUDA-aware" by construction — so direct is the default and staged
    # exists for debugging/bring-up, exactly the reference's fallback
    # role.  Env: TORCHMPI_TPU_STAGED.
    staged: bool = False

    # --- pallas kernel tilings ---------------------------------------------
    # Default block sizes for the flash-attention and fused linear+xent
    # kernels when the call site does not pass them explicitly — the knobs
    # benchmarks/autotune.py measures per platform (the reference's tuned
    # chunk constants, kernel edition).  512x512 flash blocks measured
    # fastest on a real v5e chip (2026-07-30 sweep, scripts/flash_sweep.py:
    # 8.6 ms vs 10.6 ms at 256x256 for B=4 T=4096 H=8 D=128 causal);
    # sequences shorter than a block use one tile-aligned block covering
    # the whole sequence (ops/flash._clamp_block).  128/512 are safe v5e
    # xent defaults.
    flash_block_q: int = 512
    flash_block_k: int = 512
    # 256-token xent tiles measured above the noise gate on a real v5e
    # (2026-07-31 live autotune, docs/artifacts/autotune_20260731_*.json:
    # 14.6 ms median vs 15.4 at 128, jitter ~0.6 ms); the VMEM block-fit
    # clamp (ops/xent._fit_blocks) shrinks them automatically where E is
    # too large for the scoped budget.
    xent_block_n: int = 256
    xent_block_v: int = 512
    # Fold the attention scale into q once at the kernel boundary
    # (q' = bf16(q * scale), kernels run scale=1) instead of scaling
    # every [block_q, block_k] score block on the VPU — removes one
    # full elementwise pass per block (~10% of the kernel's VPU work).
    # Numerics: q is rounded to its dtype after scaling, so scores move
    # by ~1 bf16 ulp relative; gradients stay consistent (the VJP
    # prescales fwd AND bwd recompute identically and rescales dq by
    # the chain rule).  Off by default pending a measured win on
    # silicon; the ring/residual paths ignore it (their backward
    # composes flash_attention_bwd directly at the caller's scale).
    # Env: TORCHMPI_TPU_FLASH_PRESCALE.
    flash_prescale: bool = False

    # --- fused pytree collectives ------------------------------------------
    # Upper bound (bytes) on one fused bucket when the in-axis pytree
    # collectives (allreduce/reduce/broadcast/reduce_scatter _in_axis,
    # and nn.synchronize_gradients on top of them) coalesce a tree's
    # leaves into dtype-grouped flat transfers: leaves group by dtype
    # (never promoted — mixed fp32/bf16 trees keep bf16 on the wire),
    # each group concatenates and splits into ceil(bytes/fuse_max_bytes)
    # buckets, and ONE selector-routed collective is issued per bucket.
    # O(dtypes x buckets) launches instead of O(leaves), and the
    # selector size cutover + tuning plan keys see the true fused
    # transfer size instead of per-leaf crumbs (the torchmpi coalescing
    # move; same shape as DDP's gradient buckets).  0 disables fusion
    # (per-leaf launches).  Env: TORCHMPI_TPU_FUSE_MAX_BYTES.
    fuse_max_bytes: int = 32 * 1024 * 1024

    # --- two-level (DCN) collective staging ---------------------------------
    # Chunk bound (bytes) for the pipelined hierarchical allreduce
    # (parallel/hierarchical.py): when the ICI-scattered shard exceeds
    # this, the tensor splits into chunks so the DCN transfer of chunk i
    # overlaps the ICI reduce/gather work of chunk i+1 (the reference's
    # hand-rolled chunk pipelining, two-level edition).  0 disables
    # chunking (one shard, the pre-chunking schedule — results are
    # bit-identical either way).  Env: TORCHMPI_TPU_DCN_CHUNK_BYTES.
    dcn_chunk_bytes: int = 4 * 1024 * 1024
    # Wire codec for the inter-slice (DCN) leg of two-level collectives
    # (torchmpi_tpu/compress.py — docs/HIERARCHICAL.md): "off" (default
    # — the module is never imported, dispatch is bit-identical to the
    # uncompressed path), "bf16", "int8", or "fp8".  Only the small
    # post-reduce_scatter shard crossing DCN is quantized; the ICI legs
    # always run full precision.  The gradient-sync paths additionally
    # support error-feedback residuals (the deep-gradient-compression
    # trade) via explicit residual state.  Resolved at trace/plan-build
    # time like analysis/obs/faults, so "off" costs zero runtime
    # branches.  Env: TORCHMPI_TPU_DCN_COMPRESS.
    dcn_compress: str = "off"
    # DCN legs below this stay uncompressed even when dcn_compress is
    # on — compared against the post-reduce_scatter shard (1/ici_n of
    # the tensor), the bytes that would actually be quantized (the
    # quantization + scale bookkeeping costs more than it saves on tiny
    # shards — the same latency/bandwidth cutover shape as
    # custom_min_bytes).  Env: TORCHMPI_TPU_DCN_COMPRESS_MIN_BYTES.
    dcn_compress_min_bytes: int = 64 * 1024

    # --- static collective-consistency analysis ----------------------------
    # Opt-in runtime hook for torchmpi_tpu.analysis (the SPMD
    # collective-consistency checker — docs/ANALYSIS.md): "off" (default,
    # zero added cost), "warn" (findings become Python warnings), or
    # "error" (error-severity findings raise AnalysisError before the
    # offending program compiles).  The checker runs once per jit-cache
    # entry inside the eager collectives and the step builders —
    # trace-time only, never per step.  Env: TORCHMPI_TPU_ANALYSIS.
    analysis: str = "off"

    # --- runtime observability ---------------------------------------------
    # Opt-in runtime telemetry (torchmpi_tpu.obs — docs/OBSERVABILITY.md):
    # "off" (default: one branch per collective call site, the module is
    # never even imported — same discipline as ``analysis``), "metrics"
    # (counter/histogram registry — per-collective launch+byte
    # accounting, fusion/gradsync/ZeRO/tuning/PS counters — plus the
    # deadlock flight recorder: a ring of the last obs_ring_size
    # collective events per host, dumped as JSONL/Prometheus on
    # SIGTERM/atexit for scripts/obs_tool.py blame), or "trace"
    # (metrics plus per-event user call-site attribution).
    # Env: TORCHMPI_TPU_OBS.
    obs: str = "off"
    # Directory for the per-host telemetry dumps (metrics_host*.jsonl /
    # flight_host*.jsonl).  None resolves to TORCHMPI_TPU_OBS_DIR, then
    # /tmp/torchmpi_tpu_obs.
    obs_dir: Optional[str] = None
    # Flight-recorder ring capacity (events retained per host).
    # Env: TORCHMPI_TPU_OBS_RING.
    obs_ring_size: int = 1024

    # --- elastic gang membership (torchmpi_tpu.elastic) ----------------------
    # Elastic gang resize (docs/ELASTIC.md): "off" (default — the
    # module is never imported, the dispatch path gains zero branches;
    # same discipline as ``analysis``/``obs``/``faults``) or "on"
    # (the ``elastic.run_elastic`` driver may re-form the gang at N-1
    # when a member dies — membership epochs over a two-phase
    # host-staged reconcile — and re-admit healed members at step
    # boundaries).  The knob is a consent gate for the driver layer,
    # not a dispatch-path switch: collectives never consult it.
    # Env: TORCHMPI_TPU_ELASTIC.
    elastic: str = "off"
    # Directory of the membership board (heartbeats, proposals,
    # commits, join requests — host-staged files on the shared
    # checkpoint filesystem).  None resolves to
    # ``<checkpoint directory>/membership`` inside the driver.
    # Env: TORCHMPI_TPU_ELASTIC_DIR.
    elastic_dir: Optional[str] = None
    # Poll interval for the membership board (reconcile waits, healed-
    # peer admission polls).  Env: TORCHMPI_TPU_ELASTIC_POLL.
    elastic_poll_s: float = 0.05
    # Per-round reconcile deadline: a member that posts neither its
    # proposal nor its commit within this is dropped from the proposed
    # view and the two-phase round retries one smaller (the bounded
    # part of the bounded two-phase reconcile).
    # Env: TORCHMPI_TPU_ELASTIC_DEADLINE.
    elastic_deadline_s: float = 30.0
    # Split-brain protection for the reconcile (docs/ELASTIC.md
    # "Partitions and split-brain"): "off" (default — the historical
    # drop-the-silent-and-commit behavior; a network partition can fork
    # the view.  Detection is shared by both modes: a member whose
    # board heartbeat goes stale past elastic_deadline_s relative to
    # the freshest member is death evidence either way, like the
    # watchdog lease scan — keep the deadline above the slowest
    # legitimate step/filesystem hiccup) or "majority" (a reconcile
    # may only COMMIT a view whose
    # voter set is a strict majority of the LAST COMMITTED view's
    # members; an even split breaks deterministically toward the side
    # containing the lowest-ranked member of the prior view.  A
    # minority side raises the typed ``QuorumLost`` and the driver
    # PARKS — a bounded, heartbeat-visible wait that rejoins the
    # majority's committed epoch once the partition heals, no restart
    # required).  Quorum also arms epoch FENCING: board votes,
    # heartbeats, and elastic-driven checkpoint writes from a writer
    # whose view epoch is behind the board's committed epoch raise
    # ``FencedWriterError`` and never land.  One string compare when
    # off; the fencing/partition modules are never imported.
    # Env: TORCHMPI_TPU_ELASTIC_QUORUM.
    elastic_quorum: str = "off"

    # --- payload integrity + numeric anomaly guard ---------------------------
    # torchmpi_tpu.guard (docs/GUARD.md): "off" (default — the module is
    # never imported, plan build pays one string compare, the planned
    # dispatch path gains zero branches; same discipline as
    # ``analysis``/``obs``/``faults``), "wire" (blake2b digests over
    # every host-staged payload and PS exchange, computed at the sender
    # and verified at the receiver; a mismatch raises a typed
    # ``IntegrityError`` the fault policy retries by re-staging from
    # the device buffers), "numeric" (an all-finite + norm-bound
    # tripwire fused into the synced-gradient paths — gradsync, overlap
    # buckets, ZeRO shard legs — one fused reduction per bucket), or
    # "full" (both).  Env: TORCHMPI_TPU_GUARD.
    guard: str = "off"
    # What the numeric tripwire does on a tripped bucket:
    # "skip_step" (zero the synced update and count it — training
    # continues, ``tm_guard_skipped_step_total`` records the loss) or
    # "raise" (a runtime NumericAnomalyError surfaces from the step).
    # Env: TORCHMPI_TPU_GUARD_POLICY.
    guard_numeric_policy: str = "skip_step"
    # L2-norm ceiling per checked bucket for the numeric tripwire
    # (compared against the fused sum-of-squares, so the finite check
    # and the bound are ONE reduction).  0 disables the bound — the
    # tripwire then checks finiteness only.
    # Env: TORCHMPI_TPU_GUARD_NORM_BOUND.
    guard_norm_bound: float = 0.0
    # Rolling window (steps) of the loss-spike detector used by the
    # anomaly-rewind driver (``guard.run_guarded`` /
    # ``guard.LossSpikeDetector``).  Env: TORCHMPI_TPU_GUARD_WINDOW.
    guard_spike_window: int = 16
    # Trip threshold in MADs (median absolute deviations) above the
    # rolling median.  Env: TORCHMPI_TPU_GUARD_THRESHOLD.
    guard_spike_threshold: float = 8.0

    # --- durable checkpoints (utils/checkpoint.py + utils/durable.py) --------
    # Checkpoint-resilience mode (docs/CHECKPOINT.md): "off" (default —
    # utils/durable.py is never imported, save/restore pay exactly one
    # string compare at entry; same discipline as ``analysis``/``obs``/
    # ``faults``/``guard``), "verify" (a blake2b digest over the
    # serialized checkpoint bytes is recorded in the per-file metadata
    # and re-checked on every restore — bit-rot raises a typed
    # ``CheckpointCorruptError`` the recovery walk-back treats as
    # evidence, never a silent garbage restore), or "buddy" (verify
    # PLUS each process mirrors its checkpoint pair to ``ckpt_buddies``
    # buddy locations — ranks (proc+1..K) mod world — so a restore
    # whose local file is missing or corrupt repairs from a buddy copy
    # bit-identically).  Env: TORCHMPI_TPU_CKPT_REDUNDANCY.
    ckpt_redundancy: str = "off"
    # Buddy copies per checkpoint file under ckpt_redundancy="buddy"
    # (K in the (proc+1..K) mod world placement; a single-process sim
    # mirrors to one separate on-disk location).
    # Env: TORCHMPI_TPU_CKPT_BUDDIES.
    ckpt_buddies: int = 1
    # Retention: keep only the newest K checkpoint steps per process
    # (primaries AND buddy mirrors), never pruning the step recovery
    # last settled on (the agreed/rewind step) so a chaos soak cannot
    # prune its own rewind target.  0 = keep everything (the
    # pre-retention behavior).  Only enforced when ckpt_redundancy is
    # on — off-mode saves stay untouched.  Env: TORCHMPI_TPU_CKPT_KEEP.
    ckpt_keep: int = 0

    # --- hot-state replication tier (torchmpi_tpu.hotstate) ------------------
    # In-memory (RAM-buddy) state replication above the durable disk
    # buddies (docs/HOTSTATE.md): "off" (default — the module is never
    # imported, the dispatch path gains zero branches; like
    # ``elastic``, the knob is a consent gate for a driver layer the
    # user calls explicitly) or "on" (``hotstate.enable`` may arm the
    # replicator: after each completed step a rank ships its state
    # delta — int8-quantized with an exact residual correction — to its
    # buddy's RAM, tagged (step, epoch, incarnation, blake2b digest)
    # and epoch-fenced like board writes; ``restart.recover`` and the
    # elastic shrink path then consult the RAM tier FIRST, before disk
    # buddies and primaries — the three-rung recovery ladder).
    # Env: TORCHMPI_TPU_HOTSTATE.
    hotstate: str = "off"
    # Full-snapshot cadence: every N-th stream ships the full exact
    # state instead of a delta, bounding the reconstruction chain a
    # restore must replay (and the window a single lost delta can
    # invalidate).  1 = every stream is a full snapshot.
    # Env: TORCHMPI_TPU_HOTSTATE_INTERVAL.
    hotstate_interval: int = 8
    # Per-process RAM budget (MiB) for received replicas: the inbox
    # evicts whole generations (snapshot + its delta chain), oldest
    # first — never the newest restorable generation of any peer.
    # Env: TORCHMPI_TPU_HOTSTATE_BUDGET_MB.
    hotstate_budget_mb: int = 64

    # --- collective watchdog (torchmpi_tpu.watchdog) -------------------------
    # Live hang detection over the blocking dispatch surfaces
    # (docs/WATCHDOG.md): "off" (default — the module is never
    # imported, plan build / site entry pay one string compare, the
    # planned dispatch path gains zero branches; same discipline as
    # ``analysis``/``obs``/``faults``/``guard``), "warn" (a per-process
    # monitor thread flags any in-flight collective older than
    # ``watchdog_deadline_s`` — ``tm_watchdog_*`` counters, a
    # ``watchdog`` flight event, a Python warning — and renews liveness
    # leases, but never intervenes), or "break" (warn PLUS typed
    # hang-breaking: the stalled wait is converted into a
    # ``CollectiveHangError`` the restart/elastic recovery paths heal,
    # escalating to a clean ``os._exit`` when the stall cannot be
    # unwound).  Env: TORCHMPI_TPU_WATCHDOG ("1"/"true"/"on" mean
    # "break" — the everything-armed reading a boolean opt-in wants).
    watchdog: str = "off"
    # Age at which an in-flight collective is declared stalled.  Tune
    # ABOVE the slowest legitimate collective (first-compile stalls are
    # excluded by construction — the watchdog brackets runtime waits,
    # not trace/compile time, but a genuinely slow DCN allreduce must
    # not trip it); docs/WATCHDOG.md has the tuning guidance.  The
    # break-mode ladder is staged on this value: stalled at 1x (the
    # blame --live window), typed break at 1.5x, clean-exit escalation
    # at 2.5x.  Env: TORCHMPI_TPU_WATCHDOG_DEADLINE.
    watchdog_deadline_s: float = 30.0
    # Monitor tick (scan + cooperative-break latency; lease renewal is
    # throttled separately to ~deadline/4).
    # Env: TORCHMPI_TPU_WATCHDOG_POLL.
    watchdog_poll_s: float = 0.05
    # Directory for the liveness lease files (``wd_lease_<rank>.json``
    # — read live by ``obs_tool blame --live`` and by
    # ``elastic.ElasticGang.poll`` as death evidence).  None resolves
    # to TORCHMPI_TPU_WATCHDOG_DIR, then ``elastic_dir`` (the
    # membership board — the transport still standing when the gang
    # wedged), else leases are disabled and the watchdog is
    # process-local.  Env: TORCHMPI_TPU_WATCHDOG_DIR.
    watchdog_dir: Optional[str] = None

    # --- fault injection + resilient dispatch -------------------------------
    # torchmpi_tpu.faults (docs/FAULTS.md): "off" (default — one string
    # compare per cross-host call site, the module is never imported;
    # same discipline as ``analysis``/``obs``), "policy" (resilience
    # only: bounded retries + deadline budgets + per-peer health on the
    # host-staged/PS/aio/barrier sites, nothing injected), or the path
    # of a fault-plan JSON (chaos runs: deterministic seed+site-keyed
    # injection, with the policy armed to survive it).  A corrupt or
    # version-mismatched plan raises at init.  Env: TORCHMPI_TPU_FAULTS.
    faults: str = "off"
    # Re-attempts after the first try at a faulted site (0 disables
    # retries: transient faults surface immediately, timeouts become
    # PeerTimeoutError).  Env: TORCHMPI_TPU_FAULT_RETRIES.
    fault_retries: int = 2
    # First backoff between attempts; doubles per retry, deterministic
    # jitter on top (policy.Policy).  Env: TORCHMPI_TPU_FAULT_BACKOFF.
    fault_backoff_s: float = 0.05
    # Per-site wall-clock budget: a site that makes no progress within
    # this converts the hang into a typed PeerTimeoutError carrying the
    # flight-recorder tail.  0 = unbounded (the pre-faults behavior).
    # Env: TORCHMPI_TPU_FAULT_DEADLINE.
    fault_deadline_s: float = 30.0

    # --- gradient synchronization ------------------------------------------
    # Number of buckets for bucketed/overlapped gradient allreduce.
    gradsync_buckets: int = 1
    # Chain buckets through optimization barriers so they stay distinct
    # through XLA's all-reduce combiner (measured: the combiner otherwise
    # merges sub-threshold buckets into one collective — see
    # docs/artifacts/overlap_summary.md).  Off by default: one fused
    # all-reduce is usually fastest below the combine threshold.
    gradsync_barrier: bool = False
    # Backprop-overlapped gradient sync (docs/OVERLAP.md): "off"
    # (default — the step builders run the post-backward
    # synchronize_gradients path byte-for-byte as before) or "auto"
    # (recipes' step builders compute gradients through
    # gradsync.make_overlapped_grad_fn: per-bucket allreduces fire
    # INSIDE the backward pass as each bucket's cotangents materialize
    # — reverse-parameter-order buckets, optimization-barrier chained,
    # so bucket i's communication hides under bucket i+1's backward
    # compute.  Bit-identical gradients to the synchronous path).
    # Env: TORCHMPI_TPU_GRADSYNC_OVERLAP.
    gradsync_overlap: str = "off"
    # Byte bound on one overlap bucket.  0 (default) derives it from
    # the tuning-plan size buckets: the largest measured allreduce
    # bucket for this mesh when a plan is active, else fuse_max_bytes,
    # rounded down to a plan bucket edge so every fired bucket lands on
    # a (potentially measured) plan key.
    # Env: TORCHMPI_TPU_GRADSYNC_OVERLAP_BYTES.
    gradsync_overlap_bytes: int = 0
    # Average (pmean) instead of sum (psum) in synchronize_gradients.
    gradsync_average: bool = True
    # Optional on-the-wire gradient compression: None or "bf16".
    gradsync_compress: Optional[str] = None

    # --- parameter server ---------------------------------------------------
    ps_port: int = 52312
    ps_host: str = "127.0.0.1"
    ps_num_threads: int = 2
    # Socket timeout armed on every PS client connection (seconds): a
    # wedged shard server surfaces as a failed future within this bound
    # instead of hanging wait().  0 disables.  Normalized in
    # ``runtime.init`` with the obs/analysis-style any-config env
    # pickup.  Env: TORCHMPI_TPU_PS_TIMEOUT (seconds; the legacy
    # TORCHMPI_TPU_PS_TIMEOUT_MS is still honored when the new knob is
    # unset).
    ps_timeout_s: float = 30.0

    # --- continuous-batching serving (torchmpi_tpu.serving) -----------------
    # Defaults for the off-by-default serving layer (docs/SERVING.md);
    # the package is only ever imported by explicit use — these knobs
    # just size it.  KV slot blocks per replica (the admission
    # concurrency bound; cache memory = slots x serving_slot_tokens).
    # Env: TORCHMPI_TPU_SERVING_SLOTS.
    serving_slots: int = 8
    # Tokens per slot block (prompt + generated must fit one block).
    # 0 = the model's max_len.  Shrinking below max_len needs
    # pos_emb="rope" (a learned position table is sized by max_len).
    # Env: TORCHMPI_TPU_SERVING_SLOT_TOKENS.
    serving_slot_tokens: int = 0
    # Default replica count for serving.Server (data-parallel decode
    # replicas the router spreads sessions over).
    # Env: TORCHMPI_TPU_SERVING_REPLICAS.
    serving_replicas: int = 1
    # Default sampling temperature for requests that don't set their
    # own (<= 0 = greedy).  Per-request seeds make sampled streams
    # bitwise-reproducible given (seed, prompt).
    # Env: TORCHMPI_TPU_SERVING_SAMPLE.
    serving_sample: float = 0.0
    # Speculative decoding: draft K tokens per tick and verify them in
    # one [S, K+1] target forward (0 = off).  Output is bitwise the
    # non-speculative stream at the same seed; only speed changes.
    # Env: TORCHMPI_TPU_SERVING_SPEC_K.
    serving_spec_k: int = 0
    # Bucketed prefill: right-pad prompts to pow-2 length buckets of at
    # least this many tokens, so prefill compiles are O(buckets) not
    # O(distinct lengths) (0 = off; emitted tokens are bitwise
    # unchanged either way).  Env: TORCHMPI_TPU_SERVING_PREFILL_BUCKETS.
    serving_prefill_buckets: int = 0
    # Radix prefix-sharing KV cache: capacity in shared prefix BLOCKS
    # per replica (0 = off).  Shared prompt prefixes are prefilled once
    # and reused copy-on-extend; emitted tokens stay bitwise the
    # uncached stream.  Env: TORCHMPI_TPU_SERVING_PREFIX_CACHE.
    serving_prefix_cache: int = 0
    # SLO admission control: shed arrivals (typed AdmissionRejected)
    # while live p95 TTFT exceeds this target in microseconds of the
    # scheduler's active clock (0 = admit everything).
    # Env: TORCHMPI_TPU_SERVING_SLO_TTFT_US.
    serving_slo_ttft_us: float = 0.0
    # Queue-depth autoscaling: maximum replica count the
    # FleetController may scale up to (0 = fixed fleet).  Scale-downs
    # drain through the readmit machinery — reroute without the kill.
    # Env: TORCHMPI_TPU_SERVING_AUTOSCALE.
    serving_autoscale: int = 0

    # --- distributed bring-up ----------------------------------------------
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    @staticmethod
    def from_env(**overrides) -> "Config":
        """Build a Config from ``TORCHMPI_TPU_*`` environment variables.

        Env overrides (reference analog: FFI setters callable at any time):
          TORCHMPI_TPU_BACKEND, TORCHMPI_TPU_HIERARCHICAL,
          TORCHMPI_TPU_CHUNK_BYTES, TORCHMPI_TPU_FUSE_MAX_BYTES,
          TORCHMPI_TPU_GRADSYNC_BUCKETS,
          TORCHMPI_TPU_PS_PORT, TORCHMPI_TPU_ICI_SIZE, TORCHMPI_TPU_DCN_SIZE,
          TORCHMPI_TPU_TUNING_PLAN, TORCHMPI_TPU_TUNING_ROUNDS
        """
        cfg = Config(
            backend=_env_str("TORCHMPI_TPU_BACKEND", "xla"),
            tuning_plan_path=(
                os.environ.get("TORCHMPI_TPU_TUNING_PLAN") or None),
            tuning_rounds=_env_int("TORCHMPI_TPU_TUNING_ROUNDS", 3),
            hierarchical=_env_bool("TORCHMPI_TPU_HIERARCHICAL", False),
            chunk_bytes=_env_int("TORCHMPI_TPU_CHUNK_BYTES", 4 * 1024 * 1024),
            custom_min_bytes=_env_int("TORCHMPI_TPU_CUSTOM_MIN_BYTES", 64 * 1024),
            staged=_env_bool("TORCHMPI_TPU_STAGED", False),
            analysis=_env_str("TORCHMPI_TPU_ANALYSIS", "off"),
            obs=_env_str("TORCHMPI_TPU_OBS", "off"),
            faults=_env_str("TORCHMPI_TPU_FAULTS", "off"),
            elastic=_env_str("TORCHMPI_TPU_ELASTIC", "off"),
            elastic_dir=(os.environ.get("TORCHMPI_TPU_ELASTIC_DIR")
                         or None),
            elastic_poll_s=_env_float("TORCHMPI_TPU_ELASTIC_POLL", 0.05),
            elastic_deadline_s=_env_float("TORCHMPI_TPU_ELASTIC_DEADLINE",
                                          30.0),
            elastic_quorum=_env_str("TORCHMPI_TPU_ELASTIC_QUORUM",
                                    "off"),
            guard=_env_str("TORCHMPI_TPU_GUARD", "off"),
            guard_numeric_policy=_env_str("TORCHMPI_TPU_GUARD_POLICY",
                                          "skip_step"),
            guard_norm_bound=_env_float("TORCHMPI_TPU_GUARD_NORM_BOUND",
                                        0.0),
            guard_spike_window=_env_int("TORCHMPI_TPU_GUARD_WINDOW", 16),
            guard_spike_threshold=_env_float("TORCHMPI_TPU_GUARD_THRESHOLD",
                                             8.0),
            ckpt_redundancy=_env_str("TORCHMPI_TPU_CKPT_REDUNDANCY",
                                     "off"),
            hotstate=_env_str("TORCHMPI_TPU_HOTSTATE", "off"),
            hotstate_interval=_env_int("TORCHMPI_TPU_HOTSTATE_INTERVAL",
                                       8),
            hotstate_budget_mb=_env_int(
                "TORCHMPI_TPU_HOTSTATE_BUDGET_MB", 64),
            ckpt_buddies=_env_int("TORCHMPI_TPU_CKPT_BUDDIES", 1),
            ckpt_keep=_env_int("TORCHMPI_TPU_CKPT_KEEP", 0),
            watchdog=_env_str("TORCHMPI_TPU_WATCHDOG", "off"),
            watchdog_deadline_s=_env_float(
                "TORCHMPI_TPU_WATCHDOG_DEADLINE", 30.0),
            watchdog_poll_s=_env_float("TORCHMPI_TPU_WATCHDOG_POLL",
                                       0.05),
            watchdog_dir=(os.environ.get("TORCHMPI_TPU_WATCHDOG_DIR")
                          or None),
            fault_retries=_env_int("TORCHMPI_TPU_FAULT_RETRIES", 2),
            fault_backoff_s=_env_float("TORCHMPI_TPU_FAULT_BACKOFF", 0.05),
            fault_deadline_s=_env_float("TORCHMPI_TPU_FAULT_DEADLINE",
                                        30.0),
            obs_dir=(os.environ.get("TORCHMPI_TPU_OBS_DIR") or None),
            obs_ring_size=_env_int("TORCHMPI_TPU_OBS_RING", 1024),
            fuse_max_bytes=_env_int("TORCHMPI_TPU_FUSE_MAX_BYTES",
                                    32 * 1024 * 1024),
            dcn_chunk_bytes=_env_int("TORCHMPI_TPU_DCN_CHUNK_BYTES",
                                     4 * 1024 * 1024),
            dcn_compress=_env_str("TORCHMPI_TPU_DCN_COMPRESS", "off"),
            dcn_compress_min_bytes=_env_int(
                "TORCHMPI_TPU_DCN_COMPRESS_MIN_BYTES", 64 * 1024),
            flash_prescale=_env_bool("TORCHMPI_TPU_FLASH_PRESCALE", False),
            gradsync_buckets=_env_int("TORCHMPI_TPU_GRADSYNC_BUCKETS", 1),
            gradsync_overlap=_env_str("TORCHMPI_TPU_GRADSYNC_OVERLAP",
                                      "off"),
            gradsync_overlap_bytes=_env_int(
                "TORCHMPI_TPU_GRADSYNC_OVERLAP_BYTES", 0),
            gradsync_barrier=_env_bool("TORCHMPI_TPU_GRADSYNC_BARRIER",
                                       False),
            gradsync_average=_env_bool("TORCHMPI_TPU_GRADSYNC_AVERAGE", True),
            gradsync_compress=(
                os.environ.get("TORCHMPI_TPU_GRADSYNC_COMPRESS") or None),
            serving_slots=_env_int("TORCHMPI_TPU_SERVING_SLOTS", 8),
            serving_slot_tokens=_env_int(
                "TORCHMPI_TPU_SERVING_SLOT_TOKENS", 0),
            serving_replicas=_env_int("TORCHMPI_TPU_SERVING_REPLICAS", 1),
            serving_sample=_env_float("TORCHMPI_TPU_SERVING_SAMPLE", 0.0),
            serving_spec_k=_env_int("TORCHMPI_TPU_SERVING_SPEC_K", 0),
            serving_prefill_buckets=_env_int(
                "TORCHMPI_TPU_SERVING_PREFILL_BUCKETS", 0),
            serving_prefix_cache=_env_int(
                "TORCHMPI_TPU_SERVING_PREFIX_CACHE", 0),
            serving_slo_ttft_us=_env_float(
                "TORCHMPI_TPU_SERVING_SLO_TTFT_US", 0.0),
            serving_autoscale=_env_int(
                "TORCHMPI_TPU_SERVING_AUTOSCALE", 0),
            ps_port=_env_int("TORCHMPI_TPU_PS_PORT", 52312),
            ps_host=_env_str("TORCHMPI_TPU_PS_HOST", "127.0.0.1"),
            ps_num_threads=_env_int("TORCHMPI_TPU_PS_THREADS", 2),
            ps_timeout_s=_env_float("TORCHMPI_TPU_PS_TIMEOUT", 30.0),
        )
        ici = os.environ.get("TORCHMPI_TPU_ICI_SIZE")
        if ici is not None:
            cfg.ici_size = int(ici)
        dcn = os.environ.get("TORCHMPI_TPU_DCN_SIZE")
        if dcn is not None:
            cfg.dcn_size = int(dcn)
        mesh = os.environ.get("TORCHMPI_TPU_MESH_SHAPE")
        if mesh:
            cfg.mesh_shape = {}
            for part in mesh.split(","):
                name, _, size = part.partition("=")
                if not name.strip() or not size.strip():
                    raise ValueError(
                        f"TORCHMPI_TPU_MESH_SHAPE: malformed entry {part!r} "
                        "(want name=size,name=size,...)")
                cfg.mesh_shape[name.strip()] = int(size)
        # Set by `python -m torchmpi_tpu.launch` (the mpirun analog):
        coord = os.environ.get("TORCHMPI_TPU_COORDINATOR")
        if coord:
            cfg.coordinator_address = coord
            cfg.num_processes = _env_int("TORCHMPI_TPU_NUM_PROCESSES", 1)
            cfg.process_id = _env_int("TORCHMPI_TPU_PROCESS_ID", 0)
        for k, v in overrides.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown config field {k!r}")
            setattr(cfg, k, v)
        return cfg

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)

"""Deterministic fault injection + resilient dispatch for the cross-host
paths (docs/FAULTS.md).

The reference assumed a benign MPI fabric; a production deployment
cannot.  This package is the robustness layer over every surface that
leaves the gang-scheduled SPMD world — the host-staged eager
collectives, the DCN barrier, the parameter-server sockets, and the
async host-IO executor — in two halves:

- **Injection** (:mod:`~torchmpi_tpu.faults.inject`): a seed+site-keyed
  :class:`FaultPlan` (versioned JSON; ``scripts/chaos_tool.py`` writes
  and lints them) deterministically delays, drops, corrupts-then-heals,
  or hard-fails named sites.
- **Resilience** (:mod:`~torchmpi_tpu.faults.policy`,
  :mod:`~torchmpi_tpu.faults.health`): bounded, jitter-backoff retries
  for transient errors, per-site deadline budgets that turn unbounded
  hangs into :class:`PeerTimeoutError` (carrying the obs flight-recorder
  tail), and a per-peer health ledger feeding degrade-or-raise.

Off by default and **never imported when off** — the ``analysis``/
``obs`` import discipline: every call site guards its hook behind one
``Config.faults != "off"`` string compare, so an un-opted-in build pays
one branch per dispatch and zero import cost
(``tests/test_faults.py::test_off_mode_never_imports_faults``).

Enable via ``Config.faults`` / ``TORCHMPI_TPU_FAULTS``:

- ``"policy"``       — resilience only: retries/deadlines/health armed,
  nothing injected (the production setting).
- ``<path.json>``    — a fault plan: injection AND resilience (chaos
  runs).  A corrupt/mismatched plan raises — a chaos run that silently
  tests nothing is worse than one that fails to start.

Every injected and survived event emits ``tm_fault_*`` counters and
flight-recorder events through :mod:`torchmpi_tpu.obs` (when that is
active), so ``scripts/obs_tool.py blame`` can name the injected site.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from .health import HealthLedger, PeerHealth  # noqa: F401
from .inject import (  # noqa: F401
    BOARD_SITES,
    FAULT_PLAN_VERSION,
    KINDS,
    SITES,
    CorruptPayload,
    DroppedPacket,
    FaultError,
    FaultPlan,
    FaultRule,
    InjectedFailure,
    TornWrite,
    TransientFault,
    corrupt_buffer,
    lint_plan,
    parse_partition_ranks,
)
from . import policy as policy_mod  # bound BEFORE the policy() accessor
#                                     shadows the submodule name below
from .policy import (  # noqa: F401
    PeerTimeoutError,
    Policy,
    RetriesExhaustedError,
    bounded_call,
    flight_tail,
    is_transient,
)

_lock = threading.Lock()
_plan: Optional[FaultPlan] = None
_policy = Policy()
_armed = False
_ledger = HealthLedger(
    on_transition=lambda peer, old, new: _emit(
        "health", "ledger", kind=new, peer=peer))


def active() -> bool:
    return _armed


def injecting() -> bool:
    return _armed and _plan is not None


def plan() -> Optional[FaultPlan]:
    return _plan


def current_policy() -> Policy:
    # NOT named ``policy`` — that would shadow the submodule of the
    # same name on the package object.
    return _policy


def ledger() -> HealthLedger:
    return _ledger


_NO_MASK = object()  # "computed: plan has no partition rules" sentinel


def board_partition():
    """The armed plan's board-partition visibility mask
    (``faults/partition.py`` — docs/ELASTIC.md), or None.  Built
    lazily ONCE per plan and cached on it: the partition module is
    only ever imported when a partition rule actually exists, so a
    plan without one (and every quorum-off session) never loads it."""
    p = _plan
    if not _armed or p is None:
        return None
    mask = getattr(p, "_partition_mask", None)
    if mask is None:
        if not any(r.kind == "partition" for r in p.rules):
            mask = _NO_MASK
        else:
            from . import partition

            mask = partition.build(p) or _NO_MASK
        p._partition_mask = mask  # type: ignore[attr-defined]
    return None if mask is _NO_MASK else mask


def activate(mode: str, *, retries: int = 2, backoff_s: float = 0.05,
             deadline_s: float = 30.0) -> None:
    """Arm the layer (``runtime.init``/``set_config`` call this whenever
    ``Config.faults != "off"``).  ``mode`` is ``"policy"`` or a fault-
    plan path; knobs come from ``Config.fault_*``.  Idempotent;
    re-activation with the same plan path keeps its schedule counters
    (an in-run ``set_config`` must not restart the fault schedule), a
    different path reloads."""
    global _plan, _policy, _armed
    with _lock:
        if mode == "policy":
            new_plan = None
        else:
            if _plan is not None and getattr(_plan, "_path", None) == mode:
                new_plan = _plan
            else:
                new_plan = FaultPlan.load(mode)
                new_plan._path = mode  # type: ignore[attr-defined]
        _plan = new_plan
        _policy = Policy(retries=int(retries), backoff_s=float(backoff_s),
                         deadline_s=float(deadline_s),
                         seed=_plan.seed if _plan is not None else 0)
        _armed = True


def deactivate() -> None:
    """Disarm; the health ledger's history stays readable."""
    global _plan, _armed
    with _lock:
        _armed = False
        _plan = None


def reset() -> None:
    """Disarm AND forget ledger history / plan schedule (tests)."""
    deactivate()
    _ledger.clear()


# ---------------------------------------------------------------------------
# Telemetry: tm_fault_* through obs, when obs itself is active.  A
# faults-only session must not import obs, so the dispatch goes through
# the ONE sys.modules-gated shim (utils/telemetry.py).
# ---------------------------------------------------------------------------


def _emit(action: str, site: str, *, kind: str = "", peer: str = "") -> None:
    from ..utils import telemetry

    telemetry.emit("record_fault", action, site, kind=kind, peer=peer)


# ---------------------------------------------------------------------------
# The two primitives call sites compose: fire() (injection) and
# run_site() (resilience).
# ---------------------------------------------------------------------------


def fire(site: str, payload=None, peer: str = "") -> None:
    """One arrival at an instrumented site.  With a plan armed, applies
    whatever the deterministic schedule says for this arrival: sleep
    (delay), corrupt ``payload`` + raise (corrupt), raise transient
    (drop) or hard (fail).  Without a plan (policy-only mode) this is a
    no-op beyond the counter bump of an armed site."""
    p = _plan
    if not _armed or p is None:
        return
    decided = p.decide(site)
    if decided is None:
        return
    rule, arrival = decided
    _emit("injected", site, kind=rule.kind, peer=peer)
    if rule.kind == "delay":
        _sleep(rule.delay_s)
        return
    if rule.kind == "drop":
        _sleep(rule.delay_s)
        raise DroppedPacket(
            f"injected drop at {site} (arrival {arrival}, peer silent "
            f"{rule.delay_s:.3g}s)")
    if rule.kind == "corrupt":
        corrupt_buffer(payload, p.seed, arrival)
        raise CorruptPayload(
            f"injected payload corruption at {site} (integrity check "
            f"failed)")
    if rule.kind == "corrupt_silent":
        # The silent production failure mode: bits flip, NOTHING is
        # raised — with Config.guard="off" the corruption propagates
        # and the run silently diverges; with "wire" the digest check
        # detects it downstream (docs/GUARD.md).  At the ckpt sites
        # the same kind models on-disk bit-rot (docs/CHECKPOINT.md).
        corrupt_buffer(payload, p.seed, arrival)
        return
    if rule.kind == "torn":
        raise TornWrite(
            f"injected torn write at {site} (arrival {arrival})")
    if rule.kind == "stall":
        _stall_hold(site, peer, p)
        return
    raise InjectedFailure(f"injected hard failure at {site}")


def _stall_hold(site: str, peer: str, plan: "FaultPlan") -> None:
    """The ``stall`` kind's indefinite hold: the silent hang —
    progress simply stops, nothing raises (docs/WATCHDOG.md proves the
    watchdog contract against it).  The hold registers itself with the
    armed watchdog via sys.modules (this package never imports it —
    the off-discipline runs both ways), so:

    - watchdog off   -> the site wedges until the harness timeout;
    - mode "warn"    -> the stall is flagged live (counters, flight
      event, lease) but never interrupted;
    - mode "break"   -> :func:`~torchmpi_tpu.watchdog.check_break`
      raises the typed ``CollectiveHangError`` out of the hold, which
      propagates through the site exactly like a real broken wait.

    A watchdog armed AFTER the hold started is picked up on the next
    tick.  Disarming the fault layer (or replacing the plan) releases
    the hold: the modeled wedge exists only while the chaos plan does.
    """
    import sys
    import time

    mod = None
    tok = -1
    try:
        while True:
            if not _armed or _plan is not plan:
                return  # chaos disarmed: the modeled wedge is gone
            m = sys.modules.get("torchmpi_tpu.watchdog")
            if m is not None and m.active():
                if m is not mod or not m.is_inflight(tok):
                    # First sight of an armed watchdog — or a stale
                    # token from before a deactivate/re-activate cycle:
                    # (re-)register so the new monitor sees this hold.
                    mod, tok = m, m.begin(site, op="stall", peer=peer)
                m.check_break(tok)  # raises CollectiveHangError on break
            time.sleep(0.01)
    finally:
        if mod is not None:
            mod.end(tok)


def _sleep(seconds: float) -> None:
    if seconds > 0:
        import time

        time.sleep(seconds)


def run_site(site: str, attempt: Callable[[int], Any], *,
             peer: str = "") -> Any:
    """Execute ``attempt(try_index)`` under the armed retry policy,
    recording per-peer health and emitting ``tm_fault_*`` events.  The
    attempt callable is responsible for calling :func:`fire` at its
    injection points, so a retry re-rolls the schedule (the next
    arrival at the site)."""

    def on_event(action: str, s: str) -> None:
        _emit(action, s, peer=peer)

    def tracked(i: int):
        try:
            out = attempt(i)
        except BaseException as e:
            if peer and is_transient(e):
                _ledger.record(peer, ok=False)
            raise
        if peer:
            _ledger.record(peer, ok=True)
        return out

    return policy_mod.run(site, tracked, policy=_policy, peer=peer,
                          on_event=on_event)


# ---------------------------------------------------------------------------
# Site wrappers (one per instrumented surface, so the call sites stay a
# single guarded line).
# ---------------------------------------------------------------------------


def staged_exchange(op_name: str, x_dev, n: int, params: dict,
                    compute: Callable, *, wire_guard: bool = False) -> Any:
    """The host-staged eager collective under injection + policy: the
    devices->host leg (``host_staged.gather``) and host->devices leg
    (``host_staged.scatter``) each fire per attempt; transient faults
    retry the WHOLE exchange (re-staging from the device buffers, which
    the faults cannot touch — that is what makes corrupt-then-heal
    converge back to the bit-identical result).

    ``wire_guard=True`` (``Config.guard`` in ``wire``/``full`` —
    docs/GUARD.md) brackets each leg with an end-to-end blake2b check:
    the digest is taken the moment the payload is staged (sender) and
    verified just before it is consumed (receiver), so corruption the
    fault site did NOT announce — the ``corrupt_silent`` kind, or the
    real thing — raises a typed transient :class:`~torchmpi_tpu.faults.
    integrity.IntegrityError` this same retry loop heals."""
    import numpy as np

    if wire_guard:
        from . import integrity

        watch = integrity.Watch("host_staged", "gang")

    def attempt(_i: int):
        # A WRITABLE per-attempt staging copy: an injected corrupt must
        # flip real bits in THIS attempt's buffer while the retry
        # re-stages bit-identical from the untouched source (code
        # review r6: a read-only staged copy made corrupt a silent
        # no-op, and corrupt_silent would be a no-op twice over).
        # np.asarray of a device array yields a read-only view — copy
        # it; the async worker's _RestageView.__array__ already returns
        # a fresh writable copy per call — don't copy twice (only
        # collectives calls this, always with one of those two forms).
        xs = np.asarray(x_dev)
        if not xs.flags.writeable:
            xs = np.array(xs)
        d_in = integrity.digest(xs) if wire_guard else None
        try:
            fire("host_staged.gather", payload=xs, peer="gang")
            if wire_guard:
                # Receiver side of the devices->host leg: the staged
                # buffer is about to feed the host reduction.
                integrity.verify("host_staged.gather", xs, d_in,
                                 peer="gang")
            out = compute(op_name, xs, n, **params)
            # Same writability contract for the scatter leg: several
            # host reductions return broadcast VIEWS (read-only, zero
            # strides) — a corrupt there would silently flip nothing.
            # ascontiguousarray is a no-op for the ops that already
            # return fresh buffers, and the placement path re-runs it
            # for free afterwards.
            out = np.ascontiguousarray(out)
            if not out.flags.writeable:
                out = np.array(out)
            d_out = integrity.digest(out) if wire_guard else None
            fire("host_staged.scatter", payload=out, peer="gang")
            if wire_guard:
                # Receiver side of the host->devices leg: the result is
                # about to be placed back onto the mesh.
                integrity.verify("host_staged.scatter", out, d_out,
                                 peer="gang")
        except BaseException as e:
            if wire_guard:
                watch.note(e)
            raise
        if wire_guard:
            watch.settle()
        return out

    return run_site("host_staged", attempt, peer="gang")


def guarded_barrier(name: str, sync: Callable[[], None]) -> None:
    """``runtime.barrier`` under injection + policy: the site fires per
    attempt, and the (genuinely blocking) gang sync runs under the
    deadline budget so a wedged peer surfaces as ``PeerTimeoutError``
    instead of an unbounded wait."""

    def attempt(_i: int):
        fire("runtime.barrier", peer="gang")
        return bounded_call("runtime.barrier", sync,
                            deadline_s=_policy.deadline_s, peer="gang")

    return run_site("runtime.barrier", attempt, peer="gang")


def aio_submit(submit: Callable[[], Any]) -> Any:
    """One async-IO submission under injection + policy (site
    ``aio.submit``; the submission is an enqueue, so retrying it is
    cheap and safe — the native layer sees at most one accepted
    submit)."""

    def attempt(_i: int):
        fire("aio.submit", peer="aio")
        return submit()

    return run_site("aio.submit", attempt, peer="aio")


def ps_exchange_once(peers: List[str], stage: Optional[Callable[[], Any]],
                     enqueue: Callable[..., Any], *,
                     wire_guard: bool = False) -> Any:
    """ONE staged PS enqueue: ``stage()`` materializes the flat host
    payload (None for payload-free exchanges like receive), the
    ``ps.request`` site fires on it, and — with the wire guard armed —
    the payload's sender digest is verified at the transport handoff
    before ``enqueue(payload)`` hands it to the native layer.  Not
    retried here: the caller composes it under :func:`ps_enqueue`
    (first enqueue) or :func:`ps_wait` (retransmits), so every attempt
    re-stages and re-verifies."""
    peer = ",".join(peers)
    payload = stage() if stage is not None else None
    if wire_guard and payload is not None:
        from . import integrity

        d = integrity.digest(payload)
        fire("ps.request", payload=payload, peer=peer)
        integrity.verify("ps.request", payload, d, peer=peer)
    else:
        fire("ps.request", payload=payload, peer=peer)
    return enqueue(payload) if stage is not None else enqueue()


def ps_enqueue(peers: List[str], enqueue: Callable[..., Any], *,
               stage: Optional[Callable[[], Any]] = None,
               wire_guard: bool = False) -> Any:
    """A PS client enqueue (send/receive) under injection + policy:
    ``ps.request`` fires per attempt before the sockets are touched.
    With ``stage`` the payload is re-staged per attempt (the retry
    contract that makes corrupt-then-heal converge) and — under
    ``wire_guard`` — digest-verified at the transport handoff."""
    peer = ",".join(peers)
    if wire_guard:
        from . import integrity

        watch = integrity.Watch("ps.request", peer)

    def attempt(_i: int):
        try:
            out = ps_exchange_once(peers, stage, enqueue,
                                   wire_guard=wire_guard)
        except BaseException as e:
            if wire_guard:
                watch.note(e)
            raise
        if wire_guard:
            watch.settle()
        return out

    return run_site("ps.request", attempt, peer=peer)


def ps_wait(peers: List[str], make_handle: Callable[[], Any],
            first_handle: Any) -> Any:
    """A PS exchange's wait leg under injection + policy.  The first
    attempt waits on the already-enqueued ``first_handle`` (preserving
    the async-overlap contract); a failed wait re-runs the WHOLE
    exchange via ``make_handle`` — a retransmit, not a re-wait, because
    the native future is consumed by its failure.  Peer health is
    recorded per shard endpoint from the handle's failure index, and a
    peer the ledger already calls dead stops the retransmit loop.
    ``make_handle`` owns the ``ps.request`` fire (it routes through
    :func:`ps_exchange_once`, so a retransmit re-stages — and under
    the wire guard re-verifies — exactly like a first send)."""
    state = {"handle": first_handle}
    peer_all = ",".join(peers)

    def attempt(i: int):
        if i > 0:
            # Dead peer: stop burning the budget — surface the loss as
            # a peer timeout for the restart/elastic layer.
            doomed = [p for p in peers if _ledger.decide(p) == "raise"]
            if doomed:
                raise PeerTimeoutError(
                    "ps.response", peer=doomed[0],
                    deadline_s=_policy.deadline_s,
                    flight_tail=flight_tail())
            state["handle"] = make_handle()
        fire("ps.response", peer=peer_all)
        h = state["handle"]
        try:
            out = h.wait(timeout_ms=_wait_budget_ms())
        except BaseException as e:
            bad = getattr(h, "failed_index", None)
            if bad is not None and 0 <= bad < len(peers):
                _ledger.record(peers[bad], ok=False)
            raise _as_transient(e)
        for p in peers:
            _ledger.record(p, ok=True)
        return out

    def on_event(action: str, s: str) -> None:
        _emit(action, s, peer=peer_all)

    return policy_mod.run("ps.response", attempt, policy=_policy,
                          peer=peer_all, on_event=on_event)


def _wait_budget_ms() -> int:
    """Per-attempt native-wait bound derived from the site deadline (so
    one wedged shard cannot eat the whole budget before the first
    retransmit)."""
    if _policy.deadline_s <= 0:
        return 0
    return max(1, int(_policy.deadline_s * 1000
                      / (1 + max(0, _policy.retries))))


def ckpt_write(path: str, data, commit: Callable[[], Any]) -> Any:
    """One checkpoint-file commit under injection (site ``ckpt.write``
    — utils/checkpoint.py, docs/CHECKPOINT.md).  ``data`` is the
    WRITABLE staged byte buffer (uint8 view) about to land on disk:
    ``corrupt_silent`` flips real bits that then get written and
    fsynced (bit-rot between serialize and commit — the digest
    recorded beforehand no longer matches, which is what the verified
    restore catches), ``torn`` writes a truncated prefix to the
    ``path + '.tmp'`` staging file and raises (the crash-mid-save
    artifact ``latest_step`` must ignore), and ``fail`` converts to an
    ENOSPC-flavored ``OSError``.  Deliberately NOT retried here:
    checkpoint durability belongs to the recovery protocol (walk-back
    + buddy repair), not a transport retry — a disk that ate one write
    will eat the next."""
    import errno

    try:
        fire("ckpt.write", payload=data, peer="storage")
    except TornWrite as e:
        try:
            n = max(1, len(data) // 2)
            with open(path + ".tmp", "wb") as f:
                f.write(memoryview(data).cast("B")[:n])
        except OSError:
            pass  # even the torn prefix failed — artifact optional
        raise OSError(
            errno.EIO, f"injected torn write (crash mid-save): {path}"
        ) from e
    except InjectedFailure as e:
        raise OSError(
            errno.ENOSPC, f"injected ENOSPC writing {path}") from e
    return commit()


def ckpt_read(path: str, data) -> None:
    """One checkpoint npz read under injection (site ``ckpt.read``).
    ``data`` is the writable buffer just read back from disk —
    ``corrupt_silent`` is the on-disk bit-rot a digest-verified
    restore must catch (and, with buddies, repair); ``fail`` converts
    to an EIO-flavored ``OSError`` (the dead disk).  Like the write
    side, never retried here — re-reading a rotten file yields the
    same rot; recovery's job is to find a DIFFERENT copy."""
    import errno

    try:
        fire("ckpt.read", payload=data, peer="storage")
    except InjectedFailure as e:
        raise OSError(
            errno.EIO, f"injected read failure for {path}") from e


def _as_transient(e: BaseException) -> BaseException:
    """A failed PS wait is a transport failure (reset connection, wedged
    shard, injected drop) — retryable by retransmit.  Injected faults
    and socket/timeout errors already classify; the generic
    RuntimeError the handle raises for a failed native future is
    re-flagged transient here, at the one place that knows a retransmit
    is available."""
    if is_transient(e):
        return e
    if isinstance(e, RuntimeError):
        t = TransientFault(str(e))
        t.__cause__ = e
        return t
    return e

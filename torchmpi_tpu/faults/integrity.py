"""Wire integrity: blake2b digests over host-staged payloads
(docs/GUARD.md — the ``Config.guard="wire"`` half of torchmpi_tpu.guard).

Every surface that leaves the device fabric stages its payload through
host memory — the eager staged collectives (devices -> host -> devices)
and the parameter-server client (tree -> flat f32 -> native transport).
TCP checksums the sockets and the device fabric checksums its links;
the *staged host buffer in between* is the window nothing covers, and
the failure mode there is silent: a flipped bit propagates through the
reduction and poisons every rank with no typed error to retry.

This module closes that window: :func:`digest` is computed over the
payload at the **sender** boundary (the moment it is staged), and
:func:`verify` re-hashes at the **receiver** boundary (just before the
payload is consumed — the host compute, the native enqueue).  A
mismatch raises :class:`IntegrityError`, a *transient* fault: the PR 5
retry policy re-runs the whole exchange, which re-stages from the
device buffers the corruption cannot touch — the same
corrupt-then-heal contract the injected ``corrupt`` kind proved, now
for corruption we did NOT inject (the ``corrupt_silent`` chaos kind is
its deterministic test double).

Only ever imported when ``Config.guard`` is ``"wire"``/``"full"`` or
``Config.ckpt_redundancy`` is on (utils/durable.py reuses
:func:`digest_bytes` as the ONE digest home for checkpoint files —
docs/CHECKPOINT.md) — the ``analysis``/``obs``/``faults`` import
discipline; with both knobs off this module never loads.
Telemetry (``tm_guard_*`` counters, per-site verify-latency
histograms, ``guard`` flight events carrying the digest so
``obs_tool blame`` can name the first rank whose digest diverged)
rides :mod:`torchmpi_tpu.obs` through ``sys.modules`` when obs is
active.
"""

from __future__ import annotations

import hashlib
import time
from typing import Optional

import numpy as np

from ..utils import telemetry
from .inject import TransientFault

DIGEST_BYTES = 16


class IntegrityError(TransientFault):
    """A staged payload failed its end-to-end digest check: bits
    changed between the sender's staging and the receiver's consume.
    Transient — a retry re-stages from the device buffers — and
    carries ``site``/``peer``/``bucket`` so the policy layer's health
    ledger and ``obs_tool blame`` can attribute the corruption."""

    def __init__(self, site: str, *, peer: str = "", bucket: int = 0,
                 expect: str = "", got: str = ""):
        self.site = site
        self.peer = peer
        self.bucket = int(bucket)
        self.expect = expect
        self.got = got
        peer_s = f" (peer {peer})" if peer else ""
        super().__init__(
            f"{site}{peer_s}: payload integrity check failed — digest "
            f"{got[:12]} != staged {expect[:12]} (bucket {bucket}); "
            f"bits changed between staging and consume")


def digest_bytes(data) -> str:
    """blake2b hex digest over a raw byte buffer — the checkpoint-file
    edition of :func:`digest` (utils/durable.py records it per file in
    the checkpoint metadata and re-checks it on every restore,
    docs/CHECKPOINT.md).  No shape/dtype salt: the bytes ARE the
    artifact."""
    h = hashlib.blake2b(digest_size=DIGEST_BYTES)
    h.update(memoryview(data).cast("B"))
    return h.hexdigest()


def digest(buf) -> str:
    """blake2b hex digest over a numpy payload's bytes (+ shape/dtype,
    so a torn reshape cannot alias a clean buffer).  One pass, no
    copy for C-contiguous buffers."""
    a = np.asarray(buf)
    h = hashlib.blake2b(digest_size=DIGEST_BYTES)
    h.update(str(a.dtype).encode())
    h.update(str(a.shape).encode())
    if not a.flags.c_contiguous:
        a = np.ascontiguousarray(a)
    h.update(a.view(np.uint8).reshape(-1).data)
    return h.hexdigest()


def verify(site: str, buf, expect: str, *, peer: str = "",
           bucket: int = 0) -> str:
    """Receiver-side check: re-hash ``buf`` and compare with the
    sender's ``expect``.  Records the verify latency per site and a
    ``guard`` flight event carrying the digest (the cross-host
    evidence ``obs_tool blame`` aligns); a mismatch bumps
    ``tm_guard_verify_failed_total`` and raises
    :class:`IntegrityError` (transient — the policy retries)."""
    t0 = time.monotonic()
    got = digest(buf)
    nbytes = int(np.asarray(buf).nbytes)
    _obs_latency(site, time.monotonic() - t0)
    if got != expect:
        record("verify_failed", site, peer=peer, digest=got,
               nbytes=nbytes)
        raise IntegrityError(site, peer=peer, bucket=bucket,
                             expect=expect, got=got)
    record("verified", site, peer=peer, digest=got, nbytes=nbytes)
    return got


def healed(site: str, *, peer: str = "") -> None:
    """A retried exchange whose earlier attempt failed its digest check
    just completed clean — the corrupt-then-heal close
    (``tm_guard_healed_total``)."""
    record("healed", site, peer=peer)


def record(action: str, site: str, *, peer: str = "", digest: str = "",
           nbytes: int = 0) -> None:
    """tm_guard_* through obs, when obs itself is active (the shared
    sys.modules-gated shim — a guard-only session must not import the
    telemetry it reports to)."""
    telemetry.emit("record_guard", action, site, peer=peer,
                   digest=digest, nbytes=nbytes)


def _obs_latency(site: str, seconds: float) -> None:
    telemetry.emit("record_guard_latency", site, seconds)


class Watch:
    """Per-exchange heal tracker: counts integrity failures across an
    exchange's attempts and emits ``healed`` when a later attempt
    completes clean (the evidence the guard-smoke CI job asserts)."""

    __slots__ = ("site", "peer", "failures")

    def __init__(self, site: str, peer: str = ""):
        self.site = site
        self.peer = peer
        self.failures = 0

    def note(self, e: Optional[BaseException]) -> None:
        if isinstance(e, IntegrityError):
            self.failures += 1

    def settle(self) -> None:
        """Call on attempt success: emits healed if any prior attempt
        failed its digest check."""
        if self.failures:
            healed(self.site, peer=self.peer)
            self.failures = 0

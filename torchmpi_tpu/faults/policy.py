"""Resilient dispatch: bounded retries, jittered backoff, site deadlines.

The survival half of ``torchmpi_tpu.faults`` (docs/FAULTS.md).  One
:class:`Policy` object is threaded through every instrumented site
(host-staged exchange legs, PS request/response, aio submissions, the
DCN barrier): :func:`run` executes an attempt callable, retries it on
*transient* errors — injected :class:`~torchmpi_tpu.faults.inject.
TransientFault`\\ s and the real-world socket family — with
exponential, deterministically-jittered backoff, and converts what
would be an unbounded hang into a typed :class:`PeerTimeoutError`
within the site's deadline budget.

``PeerTimeoutError`` carries the flight-recorder tail (the last events
of ``torchmpi_tpu.obs``'s deadlock ring, when obs is active): the
exception that kills a step should arrive with the evidence
``obs_tool blame`` would otherwise have to dig out of a post-mortem
dump.  ``utils/restart.run_with_restarts`` recognizes it (the
``on_peer_timeout`` path) and checkpoint-restores instead of waiting
for a watchdog kill.
"""

from __future__ import annotations

import dataclasses
import errno
import hashlib
import socket
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from .inject import FaultError

# errnos of transient socket conditions worth a retransmit.
_TRANSIENT_ERRNOS = frozenset({
    errno.ECONNRESET, errno.ECONNREFUSED, errno.ECONNABORTED,
    errno.EPIPE, errno.ETIMEDOUT, errno.EAGAIN, errno.EINTR,
    errno.ENETUNREACH, errno.EHOSTUNREACH,
})


def is_transient(e: BaseException) -> bool:
    """Would a retry plausibly succeed?  Errors that classify
    themselves (a bool ``transient`` attribute — injected
    ``FaultError``\\ s, the watchdog's ``CollectiveHangError``, which is
    timeout-flavored but must NOT be retried: re-waiting the wait that
    wedged would re-wedge) are believed; real socket errors qualify by
    class/errno; everything else does not."""
    t = getattr(e, "transient", None)
    if isinstance(t, bool):
        return t
    if isinstance(e, (socket.timeout, TimeoutError, ConnectionError,
                      BrokenPipeError)):
        return True
    if isinstance(e, OSError):
        return e.errno in _TRANSIENT_ERRNOS
    return False


def is_timeoutish(e: BaseException) -> bool:
    """Does this error mean "the peer went silent" (so exhausting
    retries is a peer timeout, not a logic failure)?  Self-classifying
    errors (a bool ``is_timeout`` attribute) are believed — the same
    duck-typed contract as :func:`is_transient`."""
    t = getattr(e, "is_timeout", None)
    if isinstance(t, bool):
        return t
    return isinstance(e, (socket.timeout, TimeoutError)) or (
        isinstance(e, OSError) and e.errno == errno.ETIMEDOUT)


class PeerTimeoutError(RuntimeError):
    """A site exceeded its deadline budget (or exhausted retries on
    peer silence): the hang, converted into a typed error carrying the
    flight-recorder tail for post-mortem alignment."""

    def __init__(self, site: str, *, peer: str = "", elapsed_s: float = 0.0,
                 deadline_s: float = 0.0,
                 last_error: Optional[BaseException] = None,
                 flight_tail: Optional[List[dict]] = None):
        self.site = site
        self.peer = peer
        self.elapsed_s = elapsed_s
        self.deadline_s = deadline_s
        self.last_error = last_error
        self.flight_tail = flight_tail or []
        tail = ""
        if self.flight_tail:
            last = self.flight_tail[-1]
            tail = (f"; last flight event #{last.get('seq')} "
                    f"{last.get('ev')}:{last.get('op')}")
        peer_s = f" (peer {peer})" if peer else ""
        super().__init__(
            f"{site}{peer_s}: no progress within {deadline_s:.3g}s "
            f"deadline (elapsed {elapsed_s:.3g}s, "
            f"last error: {last_error!r}){tail}")


class RetriesExhaustedError(RuntimeError):
    """Transient failures outlived the retry budget (and were not
    timeout-flavored — those become :class:`PeerTimeoutError`)."""

    def __init__(self, site: str, attempts: int,
                 last_error: BaseException):
        self.site = site
        self.attempts = attempts
        self.last_error = last_error
        super().__init__(
            f"{site}: still failing after {attempts} attempt(s): "
            f"{last_error!r}")


@dataclasses.dataclass
class Policy:
    """Retry/backoff/deadline knobs (``Config.fault_*``)."""

    retries: int = 2             # re-attempts AFTER the first try
    backoff_s: float = 0.05      # first backoff; doubles per retry
    backoff_max_s: float = 2.0
    jitter: float = 0.5          # +[0, jitter) fraction, deterministic
    deadline_s: float = 30.0     # per-site wall budget; 0 = unbounded
    seed: int = 0                # jitter determinism (plan seed)

    def backoff(self, site: str, attempt: int) -> float:
        """Backoff before re-attempt ``attempt`` (1-based), jittered by
        a pure hash so two runs of the same plan sleep identically."""
        base = min(self.backoff_max_s,
                   self.backoff_s * (2 ** max(0, attempt - 1)))
        h = hashlib.blake2b(f"{self.seed}:{site}:{attempt}".encode(),
                            digest_size=8).digest()
        u = int.from_bytes(h, "big") / float(1 << 64)
        return base * (1.0 + self.jitter * u)


def flight_tail(n: int = 8) -> List[dict]:
    """The last ``n`` flight-recorder events, when obs is active (via
    sys.modules — a faults-only session must not import obs).  ONE
    implementation, shared with the watchdog: ``utils/telemetry.py``."""
    from ..utils import telemetry

    return telemetry.flight_tail(n)


def run(site: str, attempt: Callable[[int], Any], *, policy: Policy,
        peer: str = "",
        on_event: Optional[Callable[[str, str], None]] = None) -> Any:
    """Execute ``attempt(try_index)`` under ``policy``.

    - transient error + budget left  -> backoff, retry
      (``on_event("retry", site)``; ``"survived"`` on eventual success)
    - transient error, budget gone   -> :class:`RetriesExhaustedError`,
      or :class:`PeerTimeoutError` when the error is timeout-flavored
    - elapsed beyond ``deadline_s``  -> :class:`PeerTimeoutError`
    - non-transient error            -> propagates untouched
    """
    t0 = time.monotonic()
    failures = 0
    while True:
        try:
            result = attempt(failures)
        except BaseException as e:  # noqa: BLE001 — classified below
            if not is_transient(e):
                raise
            failures += 1
            if on_event is not None:
                on_event("retry" if failures <= policy.retries
                         else "exhausted", site)
            elapsed = time.monotonic() - t0
            over_deadline = (policy.deadline_s > 0
                             and elapsed >= policy.deadline_s)
            if failures > policy.retries or over_deadline:
                if over_deadline or is_timeoutish(e):
                    if on_event is not None:
                        on_event("deadline", site)
                    raise PeerTimeoutError(
                        site, peer=peer, elapsed_s=elapsed,
                        deadline_s=policy.deadline_s, last_error=e,
                        flight_tail=flight_tail()) from e
                raise RetriesExhaustedError(site, failures, e) from e
            pause = policy.backoff(site, failures)
            if policy.deadline_s > 0:
                pause = min(pause, max(
                    0.0, policy.deadline_s - (time.monotonic() - t0)))
            if pause > 0:
                time.sleep(pause)
            continue
        if failures and on_event is not None:
            on_event("survived", site)
        return result


def bounded_call(site: str, fn: Callable[[], Any], *, deadline_s: float,
                 peer: str = "") -> Any:
    """Run a genuinely-blocking call (a gang barrier, a native wait with
    no timeout variant) with a wall deadline: the call runs on a helper
    thread, and if it has not returned within ``deadline_s`` the caller
    gets :class:`PeerTimeoutError` — the thread is abandoned (it cannot
    be cancelled; the caller is about to checkpoint-restore or die,
    which is the point).  ``deadline_s <= 0`` calls inline.

    Cost: one thread create/join per call, paid on the happy path too.
    Acceptable because the only guarded blocking call is the runtime
    barrier (checkpoint/init cadence, not per-step); if a per-step
    blocking site ever lands here, switch to a cached waiter thread."""
    if deadline_s <= 0:
        return fn()
    out: List[Tuple[bool, Any]] = []

    def runner():
        try:
            out.append((True, fn()))
        except BaseException as e:  # noqa: BLE001 — re-raised below
            out.append((False, e))

    th = threading.Thread(target=runner, daemon=True,
                          name=f"tm-faults-{site}")
    th.start()
    th.join(deadline_s)
    if th.is_alive():
        raise PeerTimeoutError(site, peer=peer, elapsed_s=deadline_s,
                               deadline_s=deadline_s,
                               flight_tail=flight_tail())
    ok, val = out[0]
    if not ok:
        raise val
    return val

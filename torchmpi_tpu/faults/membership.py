"""Gang-membership epochs: a host-staged board + bounded two-phase
reconcile (docs/ELASTIC.md).

The reference could tear a communicator down and re-form it (PAPER.md:
communicators are disposable); the modern gang needs the agreement half
of that — after a peer dies, the survivors must all switch to the SAME
new member set at the SAME point, and a healed peer must be able to find
the current set without asking the (possibly re-forming) gang.  Both go
through a **membership board**: a directory of small JSON files on the
shared checkpoint filesystem, the one transport that is still there
when the device fabric's gang is exactly what broke.  Every value is
staged through the host and an atomic rename — the same host-staged,
fsync-friendly discipline as ``utils/checkpoint.py`` — so a reconcile
survives the crash of any participant at any point.

Protocol (``reconcile``): a **bounded two-phase commit** per epoch.

- *Phase 1 — propose.*  Every survivor writes
  ``propose_<epoch>_<rank>.json`` naming the member set it believes in
  and the step boundary the view takes effect at.  A survivor then
  polls until every proposed member's proposal is present and equal.
- *Phase 2 — commit.*  Once the proposals agree, each survivor writes
  ``commit_<epoch>_<rank>.json``; the view is **committed** when every
  member of the proposal has committed.  A healed peer (or a late
  reader) recognizes the current view as the highest fully-committed
  epoch — commit files are never removed, so the read is race-free.
- *Bounded.*  A member that posts neither file within the deadline is
  itself declared dead: it is dropped from the set and the round
  retries at ``epoch + 1`` with the smaller membership.  Disagreeing
  proposals (two survivors observed different deaths concurrently)
  resolve the same way — the next round proposes the INTERSECTION of
  what was proposed, which all parties compute identically from the
  same files.  At most ``len(members)`` rounds can run before the set
  is a singleton, so the protocol terminates.

Partitions (docs/ELASTIC.md "Partitions and split-brain"): the
deadline path alone is not partition-safe — under a network split both
sides time out on each other and, unchecked, each would commit a
disjoint survivor view (two live gangs, two checkpoint lineages).
Three additions close it:

- **Quorum** (``reconcile(quorum_of=...)``): a view may only commit
  when its voter set is a strict majority of the LAST COMMITTED view's
  members; an even split breaks deterministically toward the side
  holding that view's lowest-ranked member.  The minority raises the
  typed :class:`QuorumLost` instead of committing (the elastic driver
  parks on it).
- **Fencing** (``faults/fencing.py``): vote and heartbeat writes carry
  the writer's claimed view epoch; with a fence armed on the board, a
  write whose epoch is behind the committed epoch raises
  ``FencedWriterError`` and never lands.
- **Board trouble != voter silence**: a deadline round in which even
  THIS rank's own freshly-posted payload is invisible means the board
  itself is unreadable (lost write, unreadable listing) — the round
  re-posts and retries the SAME epoch (bounded), instead of "dropping"
  every voter and shrinking toward ``ReconcileTimeout``.

Deterministic partitions are injectable: the ``board.read``/
``board.write`` fault sites fire on every board IO, and a ``partition``
rule (``faults/partition.py``) masks which writers' files this reader
can see — evaluated against the gang-step clock, so split-brain plans
replay bit-exactly.

Dependency-free on purpose (no jax, no numpy): the board must be
readable by a peer whose runtime is exactly what died, and by
standalone tooling.  Only ever imported when ``Config.elastic`` is on
(via ``torchmpi_tpu.elastic``) — the off path never touches it; the
fault hooks go through ``sys.modules`` (never an import), and the
fence is an attribute the elastic driver attaches only under
``elastic_quorum="majority"``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


class MembershipError(RuntimeError):
    """Base of the membership-protocol failures."""


class ReconcileDropped(MembershipError):
    """This rank was voted out of the membership during a reconcile (it
    stalled past the deadline and the survivors moved on without it).
    The correct response is the healed-peer path: finish dying, then
    :func:`torchmpi_tpu.elastic.admit` back in at a step boundary."""


class ReconcileTimeout(MembershipError):
    """A bounded wait on the board expired without the protocol making
    progress (e.g. every other participant vanished mid-round)."""


class QuorumLost(MembershipError):
    """This side of a (possible) partition cannot commit: its voter set
    is not a majority of the last committed view's members — committing
    would risk a forked view.  Carries ``epoch`` (the epoch the commit
    was refused at), ``voters`` and ``quorum_of``.  The correct
    response is the elastic driver's PARK loop: keep heartbeating,
    re-poll the board, and rejoin the majority's committed epoch once
    the partition heals (docs/ELASTIC.md)."""

    def __init__(self, *, epoch: int, voters: Sequence[int],
                 quorum_of: Sequence[int], msg: str = ""):
        self.epoch = int(epoch)
        self.voters = tuple(sorted(int(v) for v in voters))
        self.quorum_of = tuple(sorted(int(m) for m in quorum_of))
        need = len(self.quorum_of) // 2 + 1
        super().__init__(
            msg or f"quorum lost at epoch {epoch}: voters "
                   f"{list(self.voters)} are not a majority of the "
                   f"committed view's members {list(self.quorum_of)} "
                   f"(need {need}, or half containing rank "
                   f"{min(self.quorum_of) if self.quorum_of else '?'}) "
                   f"— parking instead of committing a forked view")


def has_quorum(voters: Iterable[int], quorum_of: Iterable[int]) -> bool:
    """The quorum rule (``Config.elastic_quorum="majority"``): may a
    side whose voter set is ``voters`` commit a view over the last
    committed membership ``quorum_of``?  Strict majority of
    ``quorum_of`` wins; an exact half wins only when it contains the
    LOWEST-ranked member of ``quorum_of`` — a deterministic tie-break
    every side computes identically from its own files (the prior
    members partition between the sides, so exactly one side can hold
    that rank)."""
    prior = sorted(set(int(m) for m in quorum_of))
    if not prior:
        return True  # nothing committed yet: nothing to fork from
    inter = set(prior) & {int(v) for v in voters}
    if 2 * len(inter) > len(prior):
        return True
    if 2 * len(inter) == len(prior):
        return min(prior) in inter
    return False


@dataclasses.dataclass(frozen=True)
class MembershipView:
    """One committed gang membership: ``epoch`` (monotonic view
    counter), ``members`` (sorted rank tuple), ``step`` (the step
    boundary the view took effect at — a healed peer restores the
    checkpoint of exactly this step)."""

    epoch: int
    members: Tuple[int, ...]
    step: int

    def to_json(self) -> dict:
        return {"epoch": int(self.epoch),
                "members": [int(m) for m in self.members],
                "step": int(self.step)}

    @staticmethod
    def from_json(d: dict) -> "MembershipView":
        return MembershipView(epoch=int(d["epoch"]),
                              members=tuple(sorted(int(m)
                                                   for m in d["members"])),
                              step=int(d["step"]))


def _owner_of(name: str) -> Optional[int]:
    """The rank that wrote a board file, parsed from its name (every
    per-rank file ends ``_<rank>.json``); None for shared records
    (``rewind_<round>.json`` — round numbers are not ranks, but those
    records are gang-wide anyway and a partition of them is
    meaningless, so an owner beyond the masked set is fine)."""
    stem = name[:-len(".json")] if name.endswith(".json") else name
    _, _, tail = stem.rpartition("_")
    if not tail.isdigit():
        return None
    if stem.startswith("rewind_"):
        return None  # the tail is a round number, not a rank
    return int(tail)


class Board:
    """The host-staged membership board: one directory of atomic JSON
    files.  All methods are crash-safe (write-tmp-then-rename) and
    idempotent; readers tolerate torn/missing files by ignoring them
    (an unreadable proposal is the same as an unposted one — the
    deadline handles both).

    ``reader_rank`` is the rank this process READS the board as — only
    consulted by the injected ``partition`` visibility mask (a masked
    writer's files are invisible to this reader, exactly as if the
    board filesystem were split); None disables masking for this
    handle (standalone tooling).  ``fence`` is the epoch fence the
    elastic driver attaches under ``elastic_quorum="majority"``
    (``faults/fencing.py``); vote and heartbeat writes check it.  The
    ``board.read``/``board.write`` fault sites fire on every IO when a
    plan is armed — an injected transient ``drop`` LOSES that IO (an
    unreadable listing, a write that never lands), which is what board
    trouble looks like to the protocol above."""

    def __init__(self, directory: str,
                 reader_rank: Optional[int] = None):
        self.directory = directory
        self.reader_rank = (None if reader_rank is None
                            else int(reader_rank))
        self.fence = None
        self._step = -1  # gang-step clock (note_step / heartbeat scan)
        self._clock_memo = (-1.0, -1)  # (monotonic ts, scanned clock)
        os.makedirs(directory, exist_ok=True)

    def note_step(self, step: int) -> None:
        """Advance the board's gang-step clock (the elastic driver
        calls this every step boundary) — the deterministic clock the
        partition mask's [after, heal_after) window is evaluated
        against."""
        self._step = max(self._step, int(step))

    # -- fault hooks (sys.modules — this module never imports faults) ----

    def _fire(self, site: str) -> bool:
        """One arrival at a board fault site; returns False when the
        IO is LOST (an injected transient — the board is briefly
        unreadable / the write never lands)."""
        mod = sys.modules.get("torchmpi_tpu.faults")
        if mod is None or not mod.injecting():
            return True
        try:
            mod.fire(site, peer="board")
        except Exception as e:  # noqa: BLE001 — classified, not blanket
            if getattr(e, "transient", False):
                return False
            raise
        return True

    def _mask(self):
        """The armed partition visibility mask, or None (one
        sys.modules lookup; the partition module itself only loads
        when a plan actually contains a partition rule)."""
        if self.reader_rank is None:
            return None
        mod = sys.modules.get("torchmpi_tpu.faults")
        if mod is None or not mod.injecting():
            return None
        return mod.board_partition()

    def _clock(self, fresh: bool = False) -> int:
        """The mask's step clock: this board's noted step, advanced by
        any step a member has heartbeated to the board — read RAW
        (never masked; the clock must be globally consistent so a
        parked minority still observes the heal when the majority's
        progress reaches it).  Every LISTING rescans (``_ls`` passes
        ``fresh=True``) and refreshes a memo the per-file ``_read``
        mask checks reuse — re-running the listdir-plus-parse scan for
        every file of an already-filtered listing made masked board
        scans O(N^2) (review).  The clock only ever advances, so a
        memoized value can delay observing a heal by one listing —
        never reorder it."""
        now = time.monotonic()
        memo_ts, memo_val = self._clock_memo
        if not fresh and now - memo_ts < 1.0:
            return max(memo_val, self._step)
        step = self._step
        try:
            names = os.listdir(self.directory)
        except OSError:
            return step
        for n in names:
            if not (n.startswith("hb_") and n.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.directory, n)) as f:
                    step = max(step, int(json.load(f).get("step", -1)))
            except (OSError, ValueError):
                continue
        self._step = step
        self._clock_memo = (now, step)
        return step

    # -- low-level staged IO ---------------------------------------------

    def _write(self, name: str, payload: dict) -> None:
        if not self._fire("board.write"):
            return  # injected: the write is lost before it lands
        path = os.path.join(self.directory, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _read(self, name: str) -> Optional[dict]:
        if not self._fire("board.read"):
            return None  # injected: the board is briefly unreadable
        mask = self._mask()
        if mask is not None:
            owner = _owner_of(name)
            if owner is not None and mask.masked(
                    self.reader_rank, owner, self._clock()):
                return None  # partitioned away from this reader
        try:
            with open(os.path.join(self.directory, name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _ls(self, prefix: str) -> List[str]:
        if not self._fire("board.read"):
            return []  # injected: the listing is briefly unreadable
        try:
            names = sorted(n for n in os.listdir(self.directory)
                           if n.startswith(prefix)
                           and n.endswith(".json"))
        except OSError:
            return []
        mask = self._mask()
        if mask is not None:
            clock = self._clock(fresh=True)  # once per listing;
            #                                  _read reuses the memo
            kept = []
            for n in names:
                owner = _owner_of(n)
                if owner is None or not mask.masked(
                        self.reader_rank, owner, clock):
                    kept.append(n)
            names = kept
        return names

    # -- heartbeats (the real-detection seam) ------------------------------

    def heartbeat(self, rank: int, *, epoch: int, step: int,
                  incarnation: Optional[int] = None) -> None:
        """Record liveness: ``(epoch, step, wall ts)``.  A monitor (or a
        fellow member) that sees a heartbeat stop advancing has the
        same staleness signal ``examples/downpour_elastic.py``'s
        monitor thread reads from its progress counters.  A waiting
        joiner's heartbeat also carries its per-life ``incarnation``
        (``elastic.admit``), so the gang can tell which life is
        knocking."""
        if self.fence is not None:
            # Epoch fencing (faults/fencing.py): a heartbeat CLAIMING a
            # view epoch the board committed past is a zombie's — it
            # must not land.  epoch < 0 (a waiting joiner's / parked
            # rank's beacon) claims nothing and is exempt.
            self.fence.check(epoch, what=f"heartbeat rank {int(rank)}")
        payload = {"rank": int(rank), "epoch": int(epoch),
                   "step": int(step), "ts": time.time()}
        if incarnation is not None:
            payload["incarnation"] = int(incarnation)
        self._write(f"hb_{int(rank)}.json", payload)

    def heartbeats(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for name in self._ls("hb_"):
            d = self._read(name)
            if d is not None:
                out[int(d.get("rank", -1))] = d
        return out

    # -- per-life incarnation ids (docs/ELASTIC.md) -------------------------
    #
    # Each call of ``elastic.admit`` bumps the rank's incarnation before
    # posting its join, so a join request distinguishes "the life the
    # gang already admitted" from "a NEW life of a rank whose previous
    # death has not been committed yet" — the stale-view-admission
    # ambiguity the pre-incarnation board could not resolve.

    def incarnation(self, rank: int) -> int:
        d = self._read(f"inc_{int(rank)}.json")
        return int(d.get("incarnation", 0)) if d is not None else 0

    def bump_incarnation(self, rank: int) -> int:
        n = self.incarnation(rank) + 1
        self._write(f"inc_{int(rank)}.json",
                    {"rank": int(rank), "incarnation": n,
                     "ts": time.time()})
        return n

    # -- join requests (healed peers) --------------------------------------

    def request_join(self, rank: int,
                     incarnation: Optional[int] = None) -> None:
        payload = {"rank": int(rank), "ts": time.time()}
        if incarnation is not None:
            payload["incarnation"] = int(incarnation)
        self._write(f"join_{int(rank)}.json", payload)

    def join_requests(self) -> List[int]:
        return sorted(self.join_details())

    def join_details(self) -> Dict[int, dict]:
        """Join requests with their payloads (incarnation, timestamp) —
        what :meth:`~torchmpi_tpu.elastic.ElasticGang.poll` reads to
        tell a healed joiner from a twice-dead rank's new life."""
        out: Dict[int, dict] = {}
        for name in self._ls("join_"):
            d = self._read(name)
            if d is not None:
                out[int(d["rank"])] = d
        return out

    def clear_join(self, rank: int) -> None:
        try:
            os.remove(os.path.join(self.directory,
                                   f"join_{int(rank)}.json"))
        except OSError:
            pass

    # -- rewind records (torchmpi_tpu.guard — docs/GUARD.md) ---------------
    #
    # The anomaly-rewind driver runs its agreement over this same board
    # (the transport that is still standing when the step loop's
    # numerics are exactly what broke): a tripped rank posts a rewind
    # request, every rank joins the bounded two-phase verdict
    # (guard.agree_rewind over post_value/values), and the committed
    # outcome is recorded as a ``rewind_<round>.json`` record — the
    # post-mortem row naming the step, the detection evidence, and any
    # quarantined peer.  No membership/epoch state changes: a rewind
    # restores a checkpoint in place, views and plans untouched.

    def request_rewind(self, rank: int, *, step: int,
                       stat: float = 0.0) -> None:
        """A tripped rank's signal: makes the per-step board poll of the
        untripped ranks cheap (one listdir) without them having to
        enter the agreement every step."""
        self._write(f"rewreq_{int(rank)}.json",
                    {"rank": int(rank), "step": int(step),
                     "stat": float(stat), "ts": time.time()})

    def rewind_requests(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for name in self._ls("rewreq_"):
            d = self._read(name)
            if d is not None:
                out[int(d.get("rank", -1))] = d
        return out

    def clear_rewind_request(self, rank: int) -> None:
        try:
            os.remove(os.path.join(self.directory,
                                   f"rewreq_{int(rank)}.json"))
        except OSError:
            pass

    def post_rewind_record(self, round_no: int, payload: dict) -> None:
        self._write(f"rewind_{int(round_no)}.json",
                    dict(payload, round=int(round_no), ts=time.time()))

    def rewind_records(self) -> List[dict]:
        out = []
        for name in self._ls("rewind_"):
            d = self._read(name)
            if d is not None:
                out.append(d)
        return sorted(out, key=lambda d: int(d.get("round", 0)))

    # -- two-phase state ---------------------------------------------------
    #
    # Payloads carry ``voters`` — the ranks whose agreement commits the
    # view — separately from ``members``: at a shrink they are the same
    # set (the survivors), but at an admission the deciding voters are
    # the PRE-grow members, so a healed joiner appears in ``members``
    # without having to vote in the reconcile that admits it.

    def _vote(self, phase: str, epoch: int, rank: int,
              members: Sequence[int], voters: Sequence[int],
              step: int) -> None:
        if self.fence is not None:
            # A vote AT or ABOVE the committed epoch is legitimate
            # protocol progress; one BELOW it is a zombie's stale
            # reconcile and never lands (faults/fencing.py).
            self.fence.check(epoch, what=f"{phase} rank {int(rank)}")
        self._write(f"{phase}_{int(epoch)}_{int(rank)}.json",
                    {"epoch": int(epoch),
                     "members": sorted(int(m) for m in members),
                     "voters": sorted(int(v) for v in voters),
                     "step": int(step)})

    def propose(self, epoch: int, rank: int, members: Sequence[int],
                step: int, voters: Optional[Sequence[int]] = None) -> None:
        self._vote("propose", epoch, rank, members,
                   members if voters is None else voters, step)

    def commit(self, epoch: int, rank: int, members: Sequence[int],
               step: int, voters: Optional[Sequence[int]] = None) -> None:
        self._vote("commit", epoch, rank, members,
                   members if voters is None else voters, step)

    def _votes(self, phase: str, epoch: int) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for name in self._ls(f"{phase}_{int(epoch)}_"):
            d = self._read(name)
            if d is None or "members" not in d:
                continue
            rank = int(name[:-len(".json")].split("_")[-1])
            out[rank] = d
        return out

    def proposals(self, epoch: int) -> Dict[int, dict]:
        return self._votes("propose", epoch)

    def commits(self, epoch: int) -> Dict[int, dict]:
        return self._votes("commit", epoch)

    def committed_view(self) -> Optional[MembershipView]:
        """The highest fully-committed view: every VOTER named in a
        commit payload has itself committed an equal payload for that
        epoch.  None before the first reconcile completes."""
        epochs = set()
        for name in self._ls("commit_"):
            try:
                epochs.add(int(name.split("_")[1]))
            except (IndexError, ValueError):
                continue
        for e in sorted(epochs, reverse=True):
            commits = self.commits(e)
            for d in commits.values():
                voters = [int(v) for v in d.get("voters", d["members"])]
                if voters and all(
                        v in commits and _payload_key(commits[v])
                        == _payload_key(d) for v in voters):
                    return MembershipView.from_json(d)
        return None

    # -- generic bounded min-agreement (recovery-step votes) ---------------

    def post_value(self, tag: str, rank: int, value: int) -> None:
        self._write(f"agree_{tag}_{int(rank)}.json",
                    {"rank": int(rank), "value": int(value)})

    def clear_values(self, rank: int) -> None:
        """Drop every agreement value THIS rank ever posted — called at
        gang construction so a full-gang crash-restart reusing the same
        board cannot hand a peer this rank's previous life's value
        under a re-used tag."""
        suffix = f"_{int(rank)}.json"
        for name in self._ls("agree_"):
            if name.endswith(suffix):
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    def clear_votes_above(self, rank: int, epoch: int) -> None:
        """Drop THIS rank's propose/commit files ABOVE ``epoch`` — a
        previous incarnation's aborted reconcile rounds must not poison
        the next reconcile at the same epochs (committed history at or
        below ``epoch`` stays: ``committed_view`` reads it)."""
        suffix = f"_{int(rank)}.json"
        for phase in ("propose_", "commit_"):
            for name in self._ls(phase):
                if not name.endswith(suffix):
                    continue
                try:
                    e = int(name.split("_")[1])
                except (IndexError, ValueError):
                    continue
                if e > int(epoch):
                    try:
                        os.remove(os.path.join(self.directory, name))
                    except OSError:
                        pass

    def values(self, tag: str) -> Dict[int, int]:
        out: Dict[int, int] = {}
        for name in self._ls(f"agree_{tag}_"):
            d = self._read(name)
            if d is not None:
                out[int(d["rank"])] = int(d["value"])
        return out


def _payload_key(d: dict) -> Tuple:
    return (tuple(sorted(int(m) for m in d["members"])),
            tuple(sorted(int(v) for v in d.get("voters", d["members"]))),
            int(d.get("step", 0)))


_BOARD_RETRIES = 3  # same-epoch retries when the board ITSELF is
#                     unreadable (this rank's own payload missing)


def reconcile(board: Board, local_ranks: Iterable[int],
              members: Iterable[int], *, epoch: int, step: int,
              voters: Optional[Iterable[int]] = None,
              quorum_of: Optional[Iterable[int]] = None,
              deadline_s: float = 30.0, poll_s: float = 0.05,
              ) -> MembershipView:
    """Run the bounded two-phase reconcile for ``local_ranks`` (the
    ranks THIS process speaks for — its own rank in a multi-process
    gang; every simulated member on the single-process CPU sim) until a
    view commits, and return it.

    ``members`` is the set this process proposes (survivors after a
    death; survivors plus the joiner at an admission); ``voters`` the
    subset whose agreement commits it (defaults to ``members``; at an
    admission it is the PRE-grow members, so the healed joiner need not
    vote in the reconcile that admits it); ``epoch`` the epoch to
    propose at (one above the current view).  See the module docstring
    for the drop/intersect retry semantics.  Raises
    :class:`ReconcileDropped` if every local rank was voted out, and
    :class:`ReconcileTimeout` if the voter set would shrink to empty.

    ``quorum_of`` (``Config.elastic_quorum="majority"``) is the LAST
    COMMITTED view's member set: every round's voter set must pass
    :func:`has_quorum` against it BEFORE anything commits — a side
    whose voters fell to a minority (a partition, not deaths) raises
    the typed :class:`QuorumLost` instead of forking the view.  The
    check runs at each round's entry, which covers every commit: a
    round that shrinks its voters (deadline) or resolves differing
    proposals re-enters the loop before committing."""
    members = sorted(set(int(m) for m in members))
    voters = (sorted(set(int(v) for v in voters))
              if voters is not None else list(members))
    if not set(voters) <= set(members):
        raise ValueError(
            f"voters {voters} must be a subset of members {members}")
    local = sorted(set(int(r) for r in local_ranks))
    quorum = (sorted(set(int(m) for m in quorum_of))
              if quorum_of is not None else None)
    e = int(epoch)
    step = int(step)
    while True:
        if not voters:
            raise ReconcileTimeout(
                "reconcile ran out of voters — every participant "
                "stalled past the deadline")
        if quorum is not None and not has_quorum(voters, quorum):
            raise QuorumLost(epoch=e, voters=voters, quorum_of=quorum)
        speak = [r for r in local if r in voters]
        if not speak:
            raise ReconcileDropped(
                f"ranks {local} were dropped from the membership "
                f"(survivors moved on to {members} at epoch {e})")

        def _phase(read, repost) -> Tuple[List[int], List[int], int,
                                          bool]:
            """Poll one phase until every voter's payload is present
            and equal; returns ``(members, voters, step, settled)``.
            Not settled means EVERY participant retries one epoch up
            with the returned resolution — even one whose own payload
            already matched it (committing while others move up would
            fork the view): stalled voters are dropped past the
            deadline; concurrently-differing proposals resolve to the
            member/voter INTERSECTION and the MIN step — all computed
            identically by every party from the same files, and the
            min step is the safe one: every proposer can restore a
            checkpoint at or before its own proposed boundary.

            Board trouble is NOT voter silence: a deadline at which
            even this rank's OWN payload is invisible — it posted one,
            so the board is unreadable or the write was lost — REPOSTS
            and retries the SAME epoch (bounded by _BOARD_RETRIES)
            instead of "dropping" voters that never got a chance to be
            seen; exhausted retries raise ReconcileTimeout naming the
            board, not the voters."""
            t0 = time.monotonic()
            board_tries = 0
            while True:
                got = read(e)
                if all(v in got for v in voters):
                    keys = {_payload_key(got[v]) for v in voters}
                    if len(keys) == 1:
                        return members, voters, step, True
                    inter = set(members)
                    for mset, _, _ in keys:
                        inter &= set(mset)
                    vinter = set(voters)
                    for _, vset, _ in keys:
                        vinter &= set(vset)
                    return (sorted(inter),
                            sorted(v for v in vinter if v in inter),
                            min(s for _, _, s in keys), False)
                if time.monotonic() - t0 > deadline_s:
                    if not any(r in got for r in speak):
                        board_tries += 1
                        if board_tries > _BOARD_RETRIES:
                            raise ReconcileTimeout(
                                f"membership board unreadable at epoch "
                                f"{e}: this rank's own payload is still "
                                f"missing after {board_tries} "
                                f"deadline(s) — board trouble, not "
                                f"voter silence (no voter was dropped)")
                        repost()
                        t0 = time.monotonic()
                        continue
                    alive = [v for v in voters if v in got]
                    return ([m for m in members
                             if m in alive or m not in voters], alive,
                            step, False)
                time.sleep(poll_s)

        def _post(phase_fn):
            for r in speak:
                phase_fn(e, r, members, step, voters)

        _post(board.propose)
        members, voters, step, settled = _phase(
            board.proposals, lambda: _post(board.propose))
        if not settled:
            e += 1
            continue
        _post(board.commit)
        members, voters, step, settled = _phase(
            board.commits, lambda: _post(board.commit))
        if not settled:
            e += 1
            continue
        return MembershipView(epoch=e, members=tuple(members),
                              step=int(step))


def agree_min(board: Board, tag: str, local_ranks: Iterable[int],
              members: Iterable[int], value: int, *,
              deadline_s: float = 30.0, poll_s: float = 0.05) -> int:
    """Bounded cross-member MIN of an int over the board — the
    survivors-only analog of ``checkpoint.agree_min_step`` (which runs
    over the full gang and therefore hangs forever once a member is
    dead).  ``tag`` must be unique per agreement round (the elastic
    driver derives it from (epoch, round))."""
    members = sorted(set(int(m) for m in members))
    for r in set(int(r) for r in local_ranks):
        if r in members:
            board.post_value(tag, r, value)
    t0 = time.monotonic()
    while True:
        got = board.values(tag)
        if all(m in got for m in members):
            return min(got[m] for m in members)
        if time.monotonic() - t0 > deadline_s:
            missing = [m for m in members if m not in got]
            raise ReconcileTimeout(
                f"agreement {tag!r}: members {missing} posted no value "
                f"within {deadline_s:.3g}s")
        time.sleep(poll_s)


def wait_for_view(board: Board, *, containing: Optional[int] = None,
                  min_epoch: int = 0, deadline_s: float = 30.0,
                  poll_s: float = 0.05) -> MembershipView:
    """Poll the board for a committed view (optionally one containing
    rank ``containing`` at epoch >= ``min_epoch``) — the healed peer's
    half of :func:`torchmpi_tpu.elastic.admit`."""
    t0 = time.monotonic()
    while True:
        view = board.committed_view()
        if view is not None and view.epoch >= min_epoch and (
                containing is None or containing in view.members):
            return view
        if time.monotonic() - t0 > deadline_s:
            want = ("" if containing is None
                    else f" containing rank {containing}")
            raise ReconcileTimeout(
                f"no committed view{want} appeared within "
                f"{deadline_s:.3g}s")
        time.sleep(poll_s)

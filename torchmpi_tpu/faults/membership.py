"""Gang-membership epochs: a host-staged board + bounded two-phase
reconcile (docs/ELASTIC.md).

The reference could tear a communicator down and re-form it (PAPER.md:
communicators are disposable); the modern gang needs the agreement half
of that — after a peer dies, the survivors must all switch to the SAME
new member set at the SAME point, and a healed peer must be able to find
the current set without asking the (possibly re-forming) gang.  Both go
through a **membership board**: a directory of small JSON files on the
shared checkpoint filesystem, the one transport that is still there
when the device fabric's gang is exactly what broke.  Every value is
staged through the host and an atomic rename — the same host-staged,
fsync-friendly discipline as ``utils/checkpoint.py`` — so a reconcile
survives the crash of any participant at any point.

Protocol (``reconcile``): a **bounded two-phase commit** per epoch.

- *Phase 1 — propose.*  Every survivor writes
  ``propose_<epoch>_<rank>.json`` naming the member set it believes in
  and the step boundary the view takes effect at.  A survivor then
  polls until every proposed member's proposal is present and equal.
- *Phase 2 — commit.*  Once the proposals agree, each survivor writes
  ``commit_<epoch>_<rank>.json``; the view is **committed** when every
  member of the proposal has committed.  A healed peer (or a late
  reader) recognizes the current view as the highest fully-committed
  epoch — commit files are never removed, so the read is race-free.
- *Bounded.*  A member that posts neither file within the deadline is
  itself declared dead: it is dropped from the set and the round
  retries at ``epoch + 1`` with the smaller membership.  Disagreeing
  proposals (two survivors observed different deaths concurrently)
  resolve the same way — the next round proposes the INTERSECTION of
  what was proposed, which all parties compute identically from the
  same files.  At most ``len(members)`` rounds can run before the set
  is a singleton, so the protocol terminates.

Dependency-free on purpose (no jax, no numpy): the board must be
readable by a peer whose runtime is exactly what died, and by
standalone tooling.  Only ever imported when ``Config.elastic`` is on
(via ``torchmpi_tpu.elastic``) — the off path never touches it.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


class MembershipError(RuntimeError):
    """Base of the membership-protocol failures."""


class ReconcileDropped(MembershipError):
    """This rank was voted out of the membership during a reconcile (it
    stalled past the deadline and the survivors moved on without it).
    The correct response is the healed-peer path: finish dying, then
    :func:`torchmpi_tpu.elastic.admit` back in at a step boundary."""


class ReconcileTimeout(MembershipError):
    """A bounded wait on the board expired without the protocol making
    progress (e.g. every other participant vanished mid-round)."""


@dataclasses.dataclass(frozen=True)
class MembershipView:
    """One committed gang membership: ``epoch`` (monotonic view
    counter), ``members`` (sorted rank tuple), ``step`` (the step
    boundary the view took effect at — a healed peer restores the
    checkpoint of exactly this step)."""

    epoch: int
    members: Tuple[int, ...]
    step: int

    def to_json(self) -> dict:
        return {"epoch": int(self.epoch),
                "members": [int(m) for m in self.members],
                "step": int(self.step)}

    @staticmethod
    def from_json(d: dict) -> "MembershipView":
        return MembershipView(epoch=int(d["epoch"]),
                              members=tuple(sorted(int(m)
                                                   for m in d["members"])),
                              step=int(d["step"]))


class Board:
    """The host-staged membership board: one directory of atomic JSON
    files.  All methods are crash-safe (write-tmp-then-rename) and
    idempotent; readers tolerate torn/missing files by ignoring them
    (an unreadable proposal is the same as an unposted one — the
    deadline handles both)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    # -- low-level staged IO ---------------------------------------------

    def _write(self, name: str, payload: dict) -> None:
        path = os.path.join(self.directory, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _read(self, name: str) -> Optional[dict]:
        try:
            with open(os.path.join(self.directory, name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _ls(self, prefix: str) -> List[str]:
        try:
            return sorted(n for n in os.listdir(self.directory)
                          if n.startswith(prefix) and n.endswith(".json"))
        except OSError:
            return []

    # -- heartbeats (the real-detection seam) ------------------------------

    def heartbeat(self, rank: int, *, epoch: int, step: int,
                  incarnation: Optional[int] = None) -> None:
        """Record liveness: ``(epoch, step, wall ts)``.  A monitor (or a
        fellow member) that sees a heartbeat stop advancing has the
        same staleness signal ``examples/downpour_elastic.py``'s
        monitor thread reads from its progress counters.  A waiting
        joiner's heartbeat also carries its per-life ``incarnation``
        (``elastic.admit``), so the gang can tell which life is
        knocking."""
        payload = {"rank": int(rank), "epoch": int(epoch),
                   "step": int(step), "ts": time.time()}
        if incarnation is not None:
            payload["incarnation"] = int(incarnation)
        self._write(f"hb_{int(rank)}.json", payload)

    def heartbeats(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for name in self._ls("hb_"):
            d = self._read(name)
            if d is not None:
                out[int(d.get("rank", -1))] = d
        return out

    # -- per-life incarnation ids (docs/ELASTIC.md) -------------------------
    #
    # Each call of ``elastic.admit`` bumps the rank's incarnation before
    # posting its join, so a join request distinguishes "the life the
    # gang already admitted" from "a NEW life of a rank whose previous
    # death has not been committed yet" — the stale-view-admission
    # ambiguity the pre-incarnation board could not resolve.

    def incarnation(self, rank: int) -> int:
        d = self._read(f"inc_{int(rank)}.json")
        return int(d.get("incarnation", 0)) if d is not None else 0

    def bump_incarnation(self, rank: int) -> int:
        n = self.incarnation(rank) + 1
        self._write(f"inc_{int(rank)}.json",
                    {"rank": int(rank), "incarnation": n,
                     "ts": time.time()})
        return n

    # -- join requests (healed peers) --------------------------------------

    def request_join(self, rank: int,
                     incarnation: Optional[int] = None) -> None:
        payload = {"rank": int(rank), "ts": time.time()}
        if incarnation is not None:
            payload["incarnation"] = int(incarnation)
        self._write(f"join_{int(rank)}.json", payload)

    def join_requests(self) -> List[int]:
        return sorted(self.join_details())

    def join_details(self) -> Dict[int, dict]:
        """Join requests with their payloads (incarnation, timestamp) —
        what :meth:`~torchmpi_tpu.elastic.ElasticGang.poll` reads to
        tell a healed joiner from a twice-dead rank's new life."""
        out: Dict[int, dict] = {}
        for name in self._ls("join_"):
            d = self._read(name)
            if d is not None:
                out[int(d["rank"])] = d
        return out

    def clear_join(self, rank: int) -> None:
        try:
            os.remove(os.path.join(self.directory,
                                   f"join_{int(rank)}.json"))
        except OSError:
            pass

    # -- rewind records (torchmpi_tpu.guard — docs/GUARD.md) ---------------
    #
    # The anomaly-rewind driver runs its agreement over this same board
    # (the transport that is still standing when the step loop's
    # numerics are exactly what broke): a tripped rank posts a rewind
    # request, every rank joins the bounded two-phase verdict
    # (guard.agree_rewind over post_value/values), and the committed
    # outcome is recorded as a ``rewind_<round>.json`` record — the
    # post-mortem row naming the step, the detection evidence, and any
    # quarantined peer.  No membership/epoch state changes: a rewind
    # restores a checkpoint in place, views and plans untouched.

    def request_rewind(self, rank: int, *, step: int,
                       stat: float = 0.0) -> None:
        """A tripped rank's signal: makes the per-step board poll of the
        untripped ranks cheap (one listdir) without them having to
        enter the agreement every step."""
        self._write(f"rewreq_{int(rank)}.json",
                    {"rank": int(rank), "step": int(step),
                     "stat": float(stat), "ts": time.time()})

    def rewind_requests(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for name in self._ls("rewreq_"):
            d = self._read(name)
            if d is not None:
                out[int(d.get("rank", -1))] = d
        return out

    def clear_rewind_request(self, rank: int) -> None:
        try:
            os.remove(os.path.join(self.directory,
                                   f"rewreq_{int(rank)}.json"))
        except OSError:
            pass

    def post_rewind_record(self, round_no: int, payload: dict) -> None:
        self._write(f"rewind_{int(round_no)}.json",
                    dict(payload, round=int(round_no), ts=time.time()))

    def rewind_records(self) -> List[dict]:
        out = []
        for name in self._ls("rewind_"):
            d = self._read(name)
            if d is not None:
                out.append(d)
        return sorted(out, key=lambda d: int(d.get("round", 0)))

    # -- two-phase state ---------------------------------------------------
    #
    # Payloads carry ``voters`` — the ranks whose agreement commits the
    # view — separately from ``members``: at a shrink they are the same
    # set (the survivors), but at an admission the deciding voters are
    # the PRE-grow members, so a healed joiner appears in ``members``
    # without having to vote in the reconcile that admits it.

    def _vote(self, phase: str, epoch: int, rank: int,
              members: Sequence[int], voters: Sequence[int],
              step: int) -> None:
        self._write(f"{phase}_{int(epoch)}_{int(rank)}.json",
                    {"epoch": int(epoch),
                     "members": sorted(int(m) for m in members),
                     "voters": sorted(int(v) for v in voters),
                     "step": int(step)})

    def propose(self, epoch: int, rank: int, members: Sequence[int],
                step: int, voters: Optional[Sequence[int]] = None) -> None:
        self._vote("propose", epoch, rank, members,
                   members if voters is None else voters, step)

    def commit(self, epoch: int, rank: int, members: Sequence[int],
               step: int, voters: Optional[Sequence[int]] = None) -> None:
        self._vote("commit", epoch, rank, members,
                   members if voters is None else voters, step)

    def _votes(self, phase: str, epoch: int) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for name in self._ls(f"{phase}_{int(epoch)}_"):
            d = self._read(name)
            if d is None or "members" not in d:
                continue
            rank = int(name[:-len(".json")].split("_")[-1])
            out[rank] = d
        return out

    def proposals(self, epoch: int) -> Dict[int, dict]:
        return self._votes("propose", epoch)

    def commits(self, epoch: int) -> Dict[int, dict]:
        return self._votes("commit", epoch)

    def committed_view(self) -> Optional[MembershipView]:
        """The highest fully-committed view: every VOTER named in a
        commit payload has itself committed an equal payload for that
        epoch.  None before the first reconcile completes."""
        epochs = set()
        for name in self._ls("commit_"):
            try:
                epochs.add(int(name.split("_")[1]))
            except (IndexError, ValueError):
                continue
        for e in sorted(epochs, reverse=True):
            commits = self.commits(e)
            for d in commits.values():
                voters = [int(v) for v in d.get("voters", d["members"])]
                if voters and all(
                        v in commits and _payload_key(commits[v])
                        == _payload_key(d) for v in voters):
                    return MembershipView.from_json(d)
        return None

    # -- generic bounded min-agreement (recovery-step votes) ---------------

    def post_value(self, tag: str, rank: int, value: int) -> None:
        self._write(f"agree_{tag}_{int(rank)}.json",
                    {"rank": int(rank), "value": int(value)})

    def clear_values(self, rank: int) -> None:
        """Drop every agreement value THIS rank ever posted — called at
        gang construction so a full-gang crash-restart reusing the same
        board cannot hand a peer this rank's previous life's value
        under a re-used tag."""
        suffix = f"_{int(rank)}.json"
        for name in self._ls("agree_"):
            if name.endswith(suffix):
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    def clear_votes_above(self, rank: int, epoch: int) -> None:
        """Drop THIS rank's propose/commit files ABOVE ``epoch`` — a
        previous incarnation's aborted reconcile rounds must not poison
        the next reconcile at the same epochs (committed history at or
        below ``epoch`` stays: ``committed_view`` reads it)."""
        suffix = f"_{int(rank)}.json"
        for phase in ("propose_", "commit_"):
            for name in self._ls(phase):
                if not name.endswith(suffix):
                    continue
                try:
                    e = int(name.split("_")[1])
                except (IndexError, ValueError):
                    continue
                if e > int(epoch):
                    try:
                        os.remove(os.path.join(self.directory, name))
                    except OSError:
                        pass

    def values(self, tag: str) -> Dict[int, int]:
        out: Dict[int, int] = {}
        for name in self._ls(f"agree_{tag}_"):
            d = self._read(name)
            if d is not None:
                out[int(d["rank"])] = int(d["value"])
        return out


def _payload_key(d: dict) -> Tuple:
    return (tuple(sorted(int(m) for m in d["members"])),
            tuple(sorted(int(v) for v in d.get("voters", d["members"]))),
            int(d.get("step", 0)))


def reconcile(board: Board, local_ranks: Iterable[int],
              members: Iterable[int], *, epoch: int, step: int,
              voters: Optional[Iterable[int]] = None,
              deadline_s: float = 30.0, poll_s: float = 0.05,
              ) -> MembershipView:
    """Run the bounded two-phase reconcile for ``local_ranks`` (the
    ranks THIS process speaks for — its own rank in a multi-process
    gang; every simulated member on the single-process CPU sim) until a
    view commits, and return it.

    ``members`` is the set this process proposes (survivors after a
    death; survivors plus the joiner at an admission); ``voters`` the
    subset whose agreement commits it (defaults to ``members``; at an
    admission it is the PRE-grow members, so the healed joiner need not
    vote in the reconcile that admits it); ``epoch`` the epoch to
    propose at (one above the current view).  See the module docstring
    for the drop/intersect retry semantics.  Raises
    :class:`ReconcileDropped` if every local rank was voted out, and
    :class:`ReconcileTimeout` if the voter set would shrink to empty.
    """
    members = sorted(set(int(m) for m in members))
    voters = (sorted(set(int(v) for v in voters))
              if voters is not None else list(members))
    if not set(voters) <= set(members):
        raise ValueError(
            f"voters {voters} must be a subset of members {members}")
    local = sorted(set(int(r) for r in local_ranks))
    e = int(epoch)
    step = int(step)
    while True:
        if not voters:
            raise ReconcileTimeout(
                "reconcile ran out of voters — every participant "
                "stalled past the deadline")
        speak = [r for r in local if r in voters]
        if not speak:
            raise ReconcileDropped(
                f"ranks {local} were dropped from the membership "
                f"(survivors moved on to {members} at epoch {e})")

        def _phase(read) -> Tuple[List[int], List[int], int, bool]:
            """Poll one phase until every voter's payload is present
            and equal; returns ``(members, voters, step, settled)``.
            Not settled means EVERY participant retries one epoch up
            with the returned resolution — even one whose own payload
            already matched it (committing while others move up would
            fork the view): stalled voters are dropped past the
            deadline; concurrently-differing proposals resolve to the
            member/voter INTERSECTION and the MIN step — all computed
            identically by every party from the same files, and the
            min step is the safe one: every proposer can restore a
            checkpoint at or before its own proposed boundary."""
            t0 = time.monotonic()
            while True:
                got = read(e)
                if all(v in got for v in voters):
                    keys = {_payload_key(got[v]) for v in voters}
                    if len(keys) == 1:
                        return members, voters, step, True
                    inter = set(members)
                    for mset, _, _ in keys:
                        inter &= set(mset)
                    vinter = set(voters)
                    for _, vset, _ in keys:
                        vinter &= set(vset)
                    return (sorted(inter),
                            sorted(v for v in vinter if v in inter),
                            min(s for _, _, s in keys), False)
                if time.monotonic() - t0 > deadline_s:
                    alive = [v for v in voters if v in got]
                    return ([m for m in members
                             if m in alive or m not in voters], alive,
                            step, False)
                time.sleep(poll_s)

        for r in speak:
            board.propose(e, r, members, step, voters)
        members, voters, step, settled = _phase(board.proposals)
        if not settled:
            e += 1
            continue
        for r in speak:
            board.commit(e, r, members, step, voters)
        members, voters, step, settled = _phase(board.commits)
        if not settled:
            e += 1
            continue
        return MembershipView(epoch=e, members=tuple(members),
                              step=int(step))


def agree_min(board: Board, tag: str, local_ranks: Iterable[int],
              members: Iterable[int], value: int, *,
              deadline_s: float = 30.0, poll_s: float = 0.05) -> int:
    """Bounded cross-member MIN of an int over the board — the
    survivors-only analog of ``checkpoint.agree_min_step`` (which runs
    over the full gang and therefore hangs forever once a member is
    dead).  ``tag`` must be unique per agreement round (the elastic
    driver derives it from (epoch, round))."""
    members = sorted(set(int(m) for m in members))
    for r in set(int(r) for r in local_ranks):
        if r in members:
            board.post_value(tag, r, value)
    t0 = time.monotonic()
    while True:
        got = board.values(tag)
        if all(m in got for m in members):
            return min(got[m] for m in members)
        if time.monotonic() - t0 > deadline_s:
            missing = [m for m in members if m not in got]
            raise ReconcileTimeout(
                f"agreement {tag!r}: members {missing} posted no value "
                f"within {deadline_s:.3g}s")
        time.sleep(poll_s)


def wait_for_view(board: Board, *, containing: Optional[int] = None,
                  min_epoch: int = 0, deadline_s: float = 30.0,
                  poll_s: float = 0.05) -> MembershipView:
    """Poll the board for a committed view (optionally one containing
    rank ``containing`` at epoch >= ``min_epoch``) — the healed peer's
    half of :func:`torchmpi_tpu.elastic.admit`."""
    t0 = time.monotonic()
    while True:
        view = board.committed_view()
        if view is not None and view.epoch >= min_epoch and (
                containing is None or containing in view.members):
            return view
        if time.monotonic() - t0 > deadline_s:
            want = ("" if containing is None
                    else f" containing rank {containing}")
            raise ReconcileTimeout(
                f"no committed view{want} appeared within "
                f"{deadline_s:.3g}s")
        time.sleep(poll_s)

"""Deterministic membership-board partitions (docs/ELASTIC.md).

The ``partition`` fault kind's engine: a per-rank visibility mask over
the membership board's files.  A partition rule
(:class:`~torchmpi_tpu.faults.inject.FaultRule` with
``kind="partition"`` at a ``board.*`` site) splits the gang's ranks
into groups; while the mask is active, a reader can only see board
files written by ranks on its OWN side of the split — exactly what a
network partition of the shared board filesystem looks like to each
side.  The one-way form (``"~2,3"``) makes the named ranks *deaf*
(they see nobody else's files while their own writes stay visible),
the asymmetric A-sees-B, B-doesn't-see-A case.

The window is **step-deterministic**: active from gang step
``rule.after`` until ``rule.heal_after`` (-1 = never).  The step clock
a reader evaluates the window against is the highest step ANY member
has posted to the board (its own ``note_step`` progress or a heartbeat
file's ``step`` — read RAW, never masked), so the heal is globally
consistent: a parked minority whose own step froze still observes the
heal when the majority's progress reaches ``heal_after``.  That is
what makes a chaos plan reproduce a split-brain — and its heal —
bit-exactly in gang steps on the CPU sim and across processes.

Never imported unless an armed fault plan actually contains a
partition rule (``faults.board_partition`` builds the mask lazily);
``elastic="off"`` never constructs a Board, so this module never
loads (tests/test_partition.py asserts it, subprocess included).
Dependency-free on purpose, like the rest of the faults package.
"""

from __future__ import annotations

import dataclasses
from typing import FrozenSet, List, Optional, Tuple

from .inject import FaultPlan, parse_partition_ranks


@dataclasses.dataclass(frozen=True)
class PartitionWindow:
    """One partition rule, parsed: disjoint rank ``groups`` (ranks in
    no group form the implicit "rest" side), ``one_way`` (the named
    group is deaf: it reads nobody, everybody reads it), active for
    gang steps in ``[start, heal)`` (``heal`` -1 = never lifts)."""

    groups: Tuple[FrozenSet[int], ...]
    one_way: bool
    start: int
    heal: int

    def active(self, step: int) -> bool:
        return step >= self.start and (self.heal < 0 or step < self.heal)

    def _side(self, rank: int) -> int:
        for i, g in enumerate(self.groups):
            if rank in g:
                return i
        return -1  # the implicit "rest" side

    def masked(self, reader: int, writer: int) -> bool:
        """Can ``reader`` NOT see a file ``writer`` wrote?"""
        if reader == writer:
            return False  # a rank always sees its own writes
        if self.one_way:
            # The named ranks are deaf: they cannot read anyone else's
            # files; their own writes stay visible to everyone.
            return reader in self.groups[0]
        return self._side(reader) != self._side(writer)


class BoardPartition:
    """Every partition window of one armed plan; the Board consults
    :meth:`masked` per (reader, writer, step)."""

    def __init__(self, windows: List[PartitionWindow]):
        self.windows = list(windows)

    def masked(self, reader: int, writer: int, step: int) -> bool:
        return any(w.active(step) and w.masked(reader, writer)
                   for w in self.windows)

    def any_active(self, step: int) -> bool:
        return any(w.active(step) for w in self.windows)

    def healed(self, step: int) -> bool:
        """Every window has a heal step and the clock has passed it —
        the partition is over for good (parked-rank triage)."""
        return all(w.heal >= 0 and step >= w.heal for w in self.windows)


def build(plan: FaultPlan) -> Optional[BoardPartition]:
    """Parse ``plan``'s partition rules into a mask; None when it has
    none (the common case — the Board then pays one attribute check)."""
    windows = []
    for rule in plan.rules:
        if rule.kind != "partition":
            continue
        groups, one_way = parse_partition_ranks(rule.ranks)
        windows.append(PartitionWindow(
            groups=tuple(groups), one_way=one_way,
            start=int(rule.after), heal=int(rule.heal_after)))
    return BoardPartition(windows) if windows else None

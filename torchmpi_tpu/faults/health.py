"""Per-peer health ledger: consecutive-failure accounting that feeds a
degrade-or-raise decision.

Peers are free-form strings the call sites choose — PS shard endpoints
(``host:port``), the gang pseudo-peer of the host-staged path, a file
system for aio.  The ledger is deliberately dumb: it counts, it
classifies, and it reports transitions; *what to do* about a dead peer
stays with the caller (the PS client stops retrying and raises, the
restart driver's ``on_peer_timeout`` checkpoint-restores, an elastic
Downpour job just keeps training without the peer).

Dependency-free; only ever imported when ``Config.faults`` is armed.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Tuple

STATES = ("healthy", "suspect", "dead")


@dataclasses.dataclass
class PeerHealth:
    """One peer's ledger row."""

    peer: str
    consecutive_failures: int = 0
    total_failures: int = 0
    total_successes: int = 0
    state: str = "healthy"


class HealthLedger:
    """Counts consecutive failures per peer and classifies:

    - ``healthy``  — last observation succeeded (or no observations)
    - ``suspect``  — >= ``suspect_after`` consecutive failures
    - ``dead``     — >= ``dead_after`` consecutive failures

    One success fully resets a peer (a live peer is a live peer —
    half-credit schemes just delay both detection and recovery).
    ``on_transition(peer, old, new)`` fires on every state change, which
    is how ``torchmpi_tpu.faults`` turns transitions into ``tm_fault_``
    counters without this module knowing obs exists.
    """

    def __init__(self, *, suspect_after: int = 2, dead_after: int = 4,
                 on_transition: Optional[
                     Callable[[str, str, str], None]] = None):
        if not (1 <= suspect_after <= dead_after):
            raise ValueError(
                f"need 1 <= suspect_after ({suspect_after}) <= "
                f"dead_after ({dead_after})")
        self.suspect_after = suspect_after
        self.dead_after = dead_after
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._peers: Dict[str, PeerHealth] = {}

    def _classify(self, consecutive: int) -> str:
        if consecutive >= self.dead_after:
            return "dead"
        if consecutive >= self.suspect_after:
            return "suspect"
        return "healthy"

    def record(self, peer: str, ok: bool) -> str:
        """Fold one observation; returns the peer's (new) state."""
        transition: Optional[Tuple[str, str]] = None
        with self._lock:
            h = self._peers.get(peer)
            if h is None:
                h = self._peers[peer] = PeerHealth(peer)
            if ok:
                h.total_successes += 1
                h.consecutive_failures = 0
            else:
                h.total_failures += 1
                h.consecutive_failures += 1
            new = self._classify(h.consecutive_failures)
            if new != h.state:
                transition = (h.state, new)
                h.state = new
            state = h.state
        if transition is not None and self._on_transition is not None:
            try:
                self._on_transition(peer, transition[0], transition[1])
            except Exception:  # noqa: BLE001 — telemetry never fails a step
                pass
        return state

    def state(self, peer: str) -> str:
        with self._lock:
            h = self._peers.get(peer)
            return h.state if h is not None else "healthy"

    def get(self, peer: str) -> Optional[PeerHealth]:
        with self._lock:
            h = self._peers.get(peer)
            return dataclasses.replace(h) if h is not None else None

    def peers(self) -> List[PeerHealth]:
        with self._lock:
            return [dataclasses.replace(h) for h in self._peers.values()]

    def decide(self, peer: str) -> str:
        """Degrade-or-raise verdict for the next interaction with
        ``peer``: ``"ok"`` (proceed), ``"degrade"`` (suspect — proceed
        but prefer a fallback / shed optional traffic), ``"raise"``
        (dead — stop burning the retry budget; surface the loss so the
        restart/elastic layer can act)."""
        s = self.state(peer)
        return {"healthy": "ok", "suspect": "degrade",
                "dead": "raise"}[s]

    def clear(self) -> None:
        with self._lock:
            self._peers.clear()

    # -- snapshot / restore (docs/ELASTIC.md, docs/FAULTS.md) -------------
    #
    # Peer health is evidence, and evidence must survive recovery:
    # ``utils/restart.py`` snapshots the armed ledger next to every
    # checkpoint and rehydrates it on recovery, so a process-level
    # restart does not reset every peer to ``healthy`` and re-burn the
    # full suspect->dead escalation on a peer that was already dead.

    def to_dict(self) -> dict:
        """JSON-ready snapshot of thresholds + every peer row."""
        with self._lock:
            return {
                "suspect_after": self.suspect_after,
                "dead_after": self.dead_after,
                "peers": [dataclasses.asdict(h)
                          for h in self._peers.values()],
            }

    def restore(self, d: dict) -> None:
        """Replace this ledger's peer rows with a :meth:`to_dict`
        snapshot.  Thresholds stay this ledger's own (they come from
        the live policy config, not the snapshot); states are
        re-classified against them from the snapshot's consecutive-
        failure counts.  No ``on_transition`` callbacks fire — a
        snapshot replay is old evidence, not a new observation."""
        peers = d.get("peers")
        if not isinstance(peers, list):
            raise ValueError("health snapshot has no peers list")
        rows = {}
        for p in peers:
            if not isinstance(p, dict) or "peer" not in p:
                raise ValueError(f"malformed health snapshot row: {p!r}")
            h = PeerHealth(
                peer=str(p["peer"]),
                consecutive_failures=int(p.get("consecutive_failures", 0)),
                total_failures=int(p.get("total_failures", 0)),
                total_successes=int(p.get("total_successes", 0)))
            h.state = self._classify(h.consecutive_failures)
            rows[h.peer] = h
        with self._lock:
            self._peers = rows

    @staticmethod
    def from_dict(d: dict, *, on_transition: Optional[
            Callable[[str, str, str], None]] = None) -> "HealthLedger":
        """Build a fresh ledger from a :meth:`to_dict` snapshot
        (thresholds included)."""
        led = HealthLedger(
            suspect_after=int(d.get("suspect_after", 2)),
            dead_after=int(d.get("dead_after", 4)),
            on_transition=on_transition)
        led.restore(d)
        return led

"""Epoch fencing for the membership board and the checkpoint writers
(docs/ELASTIC.md "Partitions and split-brain").

Quorum (``Config.elastic_quorum="majority"``) stops a minority from
COMMITTING a forked view; fencing stops a *zombie* — a minority rank
that parked (or wedged) through a partition heal and has not yet
noticed the majority moved on — from WRITING against the majority's
lineage in the window before it adopts the new view.  The write seam
is the fence: board votes and heartbeats (``membership.Board``) and
elastic-driven ``checkpoint.save*`` calls check the writer's claimed
view epoch against the board's highest COMMITTED epoch; a writer whose
epoch is behind gets the typed :class:`FencedWriterError` and the
write never lands.  The correct response is the park/rejoin path the
error message points at — the zombie's state is stale by definition.

Armed only by :class:`~torchmpi_tpu.elastic.ElasticGang` when quorum
is on; with ``elastic_quorum="off"`` (or elastic off) this module is
NEVER imported — ``utils/checkpoint.py`` reaches it through one
``sys.modules`` lookup per save, the same zero-cost discipline as
every other off-by-default layer (tests/test_partition.py asserts it,
subprocess included).  Dependency-free on purpose.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..utils import telemetry


class FencedWriterError(RuntimeError):
    """A write from a view epoch the board has already committed past.
    Carries ``what`` (the write that was refused), ``writer_epoch``,
    ``committed_epoch``, ``rank`` and ``incarnation``.  NOT transient
    — retrying the same stale write can never succeed; the writer must
    rejoin the committed epoch (``elastic.admit`` / the park loop)."""

    transient = False
    is_timeout = False

    def __init__(self, what: str, *, writer_epoch: int,
                 committed_epoch: int, rank: int = -1,
                 incarnation: int = 0):
        self.what = what
        self.writer_epoch = int(writer_epoch)
        self.committed_epoch = int(committed_epoch)
        self.rank = int(rank)
        self.incarnation = int(incarnation)
        super().__init__(
            f"fenced {what}: writer rank {rank} (incarnation "
            f"{incarnation}) holds view epoch {writer_epoch} but the "
            f"board has committed epoch {committed_epoch} — a majority "
            f"moved on; rejoin via the park/admit path instead of "
            f"writing (docs/ELASTIC.md)")


class Fence:
    """One armed writer identity: (board, rank, view epoch,
    incarnation).  ``check(epoch)`` is the seam — called by the Board's
    vote/heartbeat writes with the write's claimed epoch, and by the
    checkpoint seam with the fence's own epoch."""

    def __init__(self, board, rank: int, *, epoch: int,
                 incarnation: int = 0):
        self.board = board
        self.rank = int(rank)
        self.epoch = int(epoch)
        self.incarnation = int(incarnation)

    def update(self, epoch: int, incarnation: Optional[int] = None):
        """The writer adopted a new committed view (reconcile, park
        adopt, admit)."""
        self.epoch = int(epoch)
        if incarnation is not None:
            self.incarnation = int(incarnation)

    def check(self, epoch: Optional[int] = None,
              what: str = "write") -> None:
        """Raise :class:`FencedWriterError` iff the board's committed
        epoch is ahead of the write's claimed ``epoch`` (default: the
        fence's view epoch).  ``epoch < 0`` is exempt — it is the
        "no view claimed" beacon a waiting joiner / parked rank
        heartbeats with, which must stay writable precisely while the
        rank is behind.  Reads the board through the normal (masked)
        path on purpose: a zombie still inside the partition cannot
        see the majority's commits and is not fenced until the heal —
        its writes are invisible to the majority anyway."""
        e = self.epoch if epoch is None else int(epoch)
        if e < 0:
            return
        committed = self.board.committed_view()
        if committed is not None and committed.epoch > e:
            telemetry.emit("record_elastic", "fenced",
                           epoch=committed.epoch, peer=what)
            raise FencedWriterError(
                what, writer_epoch=e, committed_epoch=committed.epoch,
                rank=self.rank, incarnation=self.incarnation)


_lock = threading.Lock()
_current: Optional[Fence] = None


def arm(board, rank: int, *, epoch: int, incarnation: int = 0) -> Fence:
    """Arm fencing for this process's writer identity: attaches the
    fence to ``board`` (its vote/heartbeat writes start checking) and
    publishes it for the checkpoint seam (:func:`current`)."""
    global _current
    fence = Fence(board, rank, epoch=epoch, incarnation=incarnation)
    with _lock:
        _current = fence
    board.fence = fence
    return fence


def disarm() -> None:
    global _current
    with _lock:
        if _current is not None and getattr(_current.board, "fence",
                                            None) is _current:
            _current.board.fence = None
        _current = None


def current() -> Optional[Fence]:
    return _current


def check_save(path: str) -> None:
    """The checkpoint seam: ``utils/checkpoint.py`` calls this (via
    ``sys.modules`` — it never imports this module) before committing
    a save, so a zombie minority's checkpoint cannot land on the
    majority's lineage."""
    fence = _current
    if fence is not None:
        fence.check(what=f"checkpoint save {path}")

"""Deterministic fault plans: versioned JSON, seed+site-keyed schedules.

The injection half of ``torchmpi_tpu.faults`` (docs/FAULTS.md).  A plan
is a list of rules, each naming a *site* — one of the cross-host
dispatch points the library instruments (``SITES``) — and a fault
*kind*.  Whether the k-th arrival at a site fires is a pure function of
``(plan.seed, site, k)``: the schedule is fully determined by the plan,
so a chaos run replays bit-identically (``tests/test_faults.py`` sweeps
this), and two SPMD processes loading the same plan inject the same
faults at the same per-site hit counts.

Same versioned-schema discipline as the tuning plans
(``tuning/plancache.py``) — a ``version`` field gates the parse — but
the OPPOSITE failure posture: a corrupt or mismatched fault plan RAISES.
A tuning cache silently degrades because losing it only costs speed;
a fault plan that silently loads empty makes a chaos test silently test
nothing.

Kinds model the failures a benign-fabric port never had to survive:

- ``delay``    — sleep ``delay_s`` at the site (slow link / GC pause).
- ``drop``     — a lost packet: optional ``delay_s`` of peer silence,
  then :class:`DroppedPacket` (transient + timeout-flavored — the
  policy layer retries it, or converts it to ``PeerTimeoutError`` when
  retries are off).
- ``corrupt``  — flip bits in the staged payload (when the site carries
  one), then :class:`CorruptPayload` ("checksum mismatch"): transient,
  so a bounded ``max_hits`` makes it corrupt-then-heal.
- ``corrupt_silent`` — flip bits in the staged payload and raise
  NOTHING: the corruption a benign-fabric port never detects (a
  bit-flipped host buffer, a torn PS payload).  Only meaningful on
  payload-carrying sites (``PAYLOAD_SITES``; lint rejects the rest);
  with ``Config.guard="off"`` the run silently diverges, with
  ``"wire"`` the digest check detects it and the retry heals —
  docs/GUARD.md.
- ``fail``     — :class:`InjectedFailure`: a hard peer death.  NOT
  transient; the policy never retries it.  At the ``ckpt.*`` sites the
  site wrapper converts it to an OS-flavored error (ENOSPC on write,
  EIO on read) so the recovery stack sees what a real disk failure
  looks like.
- ``torn``     — :class:`TornWrite`: a crash mid-checkpoint-write.
  Only meaningful at ``ckpt.write`` (lint rejects it elsewhere): the
  site wrapper writes a truncated prefix of the payload to the
  ``.tmp`` staging path and raises — the artifact the atomic-rename
  discipline must leave invisible to ``latest_step``.  Hard, never
  retried (the writer is dead).
- ``stall``    — an **indefinite hold**: the site stops making progress
  and raises NOTHING — the silent hang a benign-fabric port never had
  to survive (a wedged peer mid-collective, a dead link under a
  blocking wait).  Valid at every site, payload-free ones included
  (there is nothing to flip — the failure IS the absence of progress).
  With ``Config.watchdog="off"`` the job wedges until the harness
  timeout; with the watchdog armed the hold registers itself as an
  in-flight window (via sys.modules — this module never imports the
  watchdog) so ``warn`` mode flags it live and ``break`` mode converts
  it into a typed ``CollectiveHangError`` the recovery paths heal
  (docs/WATCHDOG.md).  ``delay_s`` is meaningless on a stall (the hold
  is indefinite by definition; lint flags it).  Disarming the fault
  layer releases the hold — the wedge it models exists only while the
  chaos plan does, which is also what keeps in-process tests from
  leaking stuck threads.
- ``partition`` — a **network partition of the membership board**
  (docs/ELASTIC.md): not an arrival-fired fault but a standing
  per-rank visibility MASK the board consults on every read — reader
  rank r cannot see files written by ranks on the other side of the
  ``ranks`` split (symmetric groups ``"0,1|2,3"``, shorthand ``"2,3"``
  = those ranks vs everyone else, or one-way ``"~2,3"`` = those ranks
  are DEAF: they see nobody else's files while their own writes stay
  visible — the asymmetric case).  The window is step-deterministic:
  active from gang step ``after`` until ``heal_after`` (-1 = never
  heals); the step clock is the highest step any member has posted to
  the board (heartbeats/commits), so the heal is globally consistent
  even for a parked minority whose own step froze.  Only meaningful at
  the ``board.*`` sites (lint rejects the rest).  This is the
  split-brain reproducer: with ``Config.elastic_quorum="off"`` both
  sides commit disjoint views (the fork), with ``"majority"`` the
  minority parks and rejoins at heal.

Dependency-free on purpose (no jax, no numpy at import): loaded by
``scripts/chaos_tool.py`` standalone, and by the dump path of a dying
process.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import hashlib
import json
import threading
from typing import Dict, List, Optional, Tuple

FAULT_PLAN_VERSION = 1

# The instrumented dispatch points.  Rules may glob (``host_staged.*``);
# chaos_tool lint flags patterns that match none of these.
SITES = (
    "host_staged.gather",   # eager staged path: devices -> host leg
    "host_staged.scatter",  # eager staged path: host -> devices leg
    "runtime.barrier",      # the DCN barrier
    "ps.request",           # parameter-server client enqueue leg
    "ps.response",          # parameter-server client wait leg
    "aio.submit",           # async host-IO submission
    "serving.replica",      # one replica decode step in the continuous-
    #                         batching server (torchmpi_tpu/serving/):
    #                         drop = transient step failure (health
    #                         ledger counts it), fail = the replica dies
    #                         and its sessions drain + re-route
    "serving.admit",        # one arrival at the serving admission gate
    #                         (scheduler._gate, peer = the request id):
    #                         ANY fault verdict at the door is a SHED —
    #                         the request completes immediately with a
    #                         typed rejection, exactly the SLO
    #                         backpressure path (drop = a lost
    #                         admission RPC, fail = the gate refusing).
    #                         Payload-free: there is nothing to corrupt
    #                         at the door
    "elastic.member",       # one member liveness check per step
    #                         boundary in the elastic gang driver
    #                         (torchmpi_tpu/elastic.py): arrival
    #                         ordinal = step * n_members + member
    #                         index, so `fail` with after=k kills a
    #                         SPECIFIC rank at a SPECIFIC step
    #                         (chaos_tool gen --shrink computes k);
    #                         drop = a missed heartbeat the health
    #                         ledger escalates healthy->suspect->dead
    "ckpt.write",           # one checkpoint-file commit (npz or
    #                         metadata json, primaries and buddy
    #                         mirrors alike — utils/checkpoint.py /
    #                         utils/durable.py, docs/CHECKPOINT.md):
    #                         corrupt_silent = bit-rot between
    #                         serialize and fsync, `torn` = a
    #                         truncated-prefix .tmp artifact + crash
    #                         (the mid-save kill), `fail` = an
    #                         ENOSPC-flavored OSError
    "ckpt.read",            # one checkpoint npz read (restore /
    #                         restore_sharded / buddy-repair source):
    #                         corrupt_silent = on-disk bit-rot the
    #                         digest verify must catch, `fail` = an
    #                         EIO-flavored dead disk
    "board.write",          # one membership-board file commit
    #                         (faults/membership.py, docs/ELASTIC.md):
    #                         heartbeats, proposals, commits, joins —
    #                         `drop` loses the write (the file never
    #                         lands), `delay`/`stall` model a slow or
    #                         wedged board filesystem, and `partition`
    #                         rules key their visibility mask here
    "board.read",           # one membership-board listing/file read:
    #                         `drop` = the board is briefly unreadable
    #                         (the reconcile must retry the SAME epoch,
    #                         not vote everyone out), `partition` masks
    #                         which writers this reader can see
    "hotstate.send",        # one hot-state replica shipped to a buddy's
    #                         RAM (torchmpi_tpu/hotstate/,
    #                         docs/HOTSTATE.md): `drop` loses the
    #                         stream message (the chain self-heals at
    #                         the next full snapshot), `corrupt_silent`
    #                         flips bits in the staged delta payload
    #                         before it leaves the sender, `stall`
    #                         models a wedged transport the watchdog
    #                         must flag
    "hotstate.recv",        # the buddy-side receipt of one replica:
    #                         `corrupt_silent` = a bit-flipped RAM
    #                         buffer the digest verify must catch at
    #                         restore time (the ladder falls to the
    #                         disk rung instead of restoring poisoned
    #                         state), `drop` = the receiver missed the
    #                         message, `fail` = the buddy is gone
)

KINDS = ("delay", "drop", "corrupt", "corrupt_silent", "fail", "torn",
         "stall", "partition")

# Sites a ``partition`` rule may target: the membership board is the
# only surface with per-rank file ownership to mask.
BOARD_SITES = ("board.read", "board.write")

# Sites whose ``fire()`` call passes a real writable payload buffer —
# the only sites where a ``corrupt``/``corrupt_silent`` rule can flip
# bits (and where the wire-integrity guard has something to digest).
PAYLOAD_SITES = (
    "host_staged.gather",
    "host_staged.scatter",
    "ps.request",
    "ckpt.write",
    "ckpt.read",
    "hotstate.send",
    "hotstate.recv",
)


class FaultError(RuntimeError):
    """Base of every injected fault."""

    transient = False
    is_timeout = False


class TransientFault(FaultError):
    """Injected fault a retry can survive (the policy layer's cue)."""

    transient = True


class DroppedPacket(TransientFault):
    """A dropped packet: the peer went silent and a timeout fired.
    Timeout-flavored, so exhausting retries on it converts to
    ``PeerTimeoutError`` rather than a bare retries-exhausted error."""

    is_timeout = True


class CorruptPayload(TransientFault):
    """Payload failed its integrity check (bits were really flipped when
    the site carries a buffer — a caller that swallows this error sees
    the corruption)."""


class InjectedFailure(FaultError):
    """Hard failure: the peer is gone.  Never retried."""


class TornWrite(InjectedFailure):
    """A crash mid-checkpoint-write (``torn`` kind, ``ckpt.write``
    only): the site wrapper leaves a truncated ``.tmp`` artifact and
    raises this.  Hard — the writing process is modeled as dead."""


@dataclasses.dataclass
class FaultRule:
    """One scheduled fault at one site (pattern)."""

    site: str                 # exact site name or fnmatch glob
    kind: str                 # delay | drop | corrupt | fail
    prob: float = 1.0         # per-hit firing probability
    after: int = 0            # skip the first ``after`` arrivals
    #                           (partition: the START step of the mask)
    max_hits: int = 1         # fire at most this many times (0 = never,
    #                           -1 = unbounded) — the "heal" knob
    delay_s: float = 0.0      # sleep for delay/drop kinds
    ranks: str = ""           # partition only: the visibility split —
    #                           "2,3" (those vs the rest), "0,1|2,3"
    #                           (explicit symmetric groups), "~2,3"
    #                           (one-way: those ranks go deaf)
    heal_after: int = -1      # partition only: the step the mask lifts
    #                           at (-1 = never heals)

    def validate(self) -> None:
        if not self.site or not isinstance(self.site, str):
            raise ValueError(f"rule has no site: {self!r}")
        if self.kind not in KINDS:
            raise ValueError(
                f"rule kind {self.kind!r} not one of {KINDS}")
        if not (0.0 <= float(self.prob) <= 1.0):
            raise ValueError(f"rule prob {self.prob!r} outside [0, 1]")
        if int(self.after) < 0:
            raise ValueError(f"rule after {self.after!r} must be >= 0")
        if int(self.max_hits) < -1:
            raise ValueError(
                f"rule max_hits {self.max_hits!r} must be >= -1")
        if float(self.delay_s) < 0:
            raise ValueError(f"rule delay_s {self.delay_s!r} must be >= 0")
        if int(self.heal_after) < -1:
            raise ValueError(
                f"rule heal_after {self.heal_after!r} must be >= -1")
        if self.kind == "partition":
            if not str(self.ranks).strip():
                raise ValueError(
                    f"partition rule needs a ranks split: {self!r}")
            parse_partition_ranks(self.ranks)  # raises on bad grammar
            if 0 <= int(self.heal_after) <= int(self.after):
                raise ValueError(
                    f"partition heal_after {self.heal_after} must be "
                    f"> after {self.after} (or -1 = never heals)")
        elif str(self.ranks).strip():
            raise ValueError(
                f"rule ranks {self.ranks!r} is only meaningful on "
                f"kind 'partition'")

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        # The partition-only fields are omitted at their defaults so a
        # pre-partition plan round-trips byte-identically (and readers
        # of older dumps never meet fields they cannot hold).
        if not d.get("ranks"):
            d.pop("ranks", None)
        if d.get("heal_after", -1) == -1:
            d.pop("heal_after", None)
        return d

    @staticmethod
    def from_json(d: dict) -> "FaultRule":
        if not isinstance(d, dict):
            raise ValueError(f"fault rule is not an object: {d!r}")
        fields = {f.name for f in dataclasses.fields(FaultRule)}
        unknown = sorted(set(d) - fields)
        if unknown:
            raise ValueError(f"fault rule has unknown fields {unknown}")
        rule = FaultRule(**d)
        rule.validate()
        return rule


def parse_partition_ranks(spec: str):
    """Parse a partition rule's ``ranks`` grammar into
    ``(groups, one_way)``: ``groups`` is a list of disjoint rank sets,
    ``one_way`` True for the ``~`` (deaf-ranks) form.  Grammar:
    ``"2,3"`` (one group vs. the implicit rest), ``"0,1|2,3"``
    (explicit symmetric groups), ``"~2,3"`` (one-way: the named ranks
    cannot READ anyone else's files; their writes stay visible — the
    asymmetric A-sees-B, B-doesn't-see-A case).  Raises ValueError on
    anything else."""
    s = str(spec).strip()
    one_way = s.startswith("~")
    if one_way:
        s = s[1:]
    groups = []
    seen: set = set()
    for part in s.split("|"):
        try:
            g = frozenset(int(r) for r in part.split(",") if r.strip())
        except ValueError:
            raise ValueError(
                f"partition ranks {spec!r}: want RANK[,RANK...] groups "
                f"separated by '|' (optional leading '~' for one-way)"
            ) from None
        if not g:
            raise ValueError(f"partition ranks {spec!r}: empty group")
        if g & seen:
            raise ValueError(
                f"partition ranks {spec!r}: rank in two groups")
        if any(r < 0 for r in g):
            raise ValueError(
                f"partition ranks {spec!r}: ranks must be >= 0")
        seen |= g
        groups.append(g)
    if one_way and len(groups) != 1:
        raise ValueError(
            f"partition ranks {spec!r}: the one-way '~' form takes "
            f"exactly one group (the deaf ranks)")
    return groups, one_way


def decision(seed: int, site: str, hit: int) -> float:
    """Uniform [0, 1) draw for the ``hit``-th arrival at ``site`` — a
    pure hash of (seed, site, hit), the whole determinism story."""
    h = hashlib.blake2b(f"{seed}:{site}:{hit}".encode(),
                        digest_size=8).digest()
    return int.from_bytes(h, "big") / float(1 << 64)


@dataclasses.dataclass
class FaultPlan:
    """A versioned, seeded rule set plus the per-site hit counters that
    realize its deterministic schedule."""

    seed: int = 0
    rules: List[FaultRule] = dataclasses.field(default_factory=list)
    note: str = ""

    def __post_init__(self) -> None:
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}   # arrivals per site
        self._fired: Dict[int, int] = {}  # fires per rule index —
        # max_hits bounds a RULE's total fires across every site its
        # pattern matches, not per site (a glob rule with max_hits=2
        # firing 2x per matched site would silently exceed the retry
        # budget the plan was written against)

    # -- schedule --------------------------------------------------------

    def arrivals(self, site: str) -> int:
        return self._hits.get(site, 0)

    def decide(self, site: str) -> Optional[Tuple[FaultRule, int]]:
        """Register one arrival at ``site``; return ``(rule, arrival)``
        for the rule that fires on it, if any (first matching rule
        wins).  Deterministic in the per-site arrival ordinal — which is
        why the ordinal is returned from under the lock: a caller
        re-reading the counter afterwards would race other threads'
        arrivals and report (or corrupt with) the wrong ordinal."""
        with self._lock:
            hit = self._hits.get(site, 0)
            self._hits[site] = hit + 1
            for i, rule in enumerate(self.rules):
                if rule.kind == "partition":
                    continue  # a standing mask, not an arrival-fired
                    #           fault (faults.board_partition serves it)
                if not fnmatch.fnmatchcase(site, rule.site):
                    continue
                if hit < rule.after:
                    continue
                fired = self._fired.get(i, 0)
                if rule.max_hits >= 0 and fired >= rule.max_hits:
                    continue
                if decision(self.seed, site, hit) >= rule.prob:
                    continue
                self._fired[i] = fired + 1
                return rule, hit
            return None

    def reset_schedule(self) -> None:
        """Forget arrival/fire counters (a fresh run of the same plan)."""
        with self._lock:
            self._hits.clear()
            self._fired.clear()

    # -- persistence -----------------------------------------------------

    def to_json(self) -> dict:
        return {"version": FAULT_PLAN_VERSION, "seed": int(self.seed),
                "note": self.note,
                "rules": [r.to_json() for r in self.rules]}

    @staticmethod
    def from_json(data: dict) -> "FaultPlan":
        if not isinstance(data, dict):
            raise ValueError("fault plan is not a JSON object")
        if data.get("version") != FAULT_PLAN_VERSION:
            raise ValueError(
                f"fault plan version {data.get('version')!r} != "
                f"{FAULT_PLAN_VERSION}")
        rules = data.get("rules")
        if not isinstance(rules, list):
            raise ValueError("fault plan has no rules list")
        return FaultPlan(
            seed=int(data.get("seed", 0)),
            note=str(data.get("note", "")),
            rules=[FaultRule.from_json(r) for r in rules])

    @staticmethod
    def load(path: str) -> "FaultPlan":
        """Parse ``path``; raises (OSError/ValueError) on anything wrong
        — see the module docstring for why this is NOT never-crash."""
        with open(path) as f:
            try:
                data = json.load(f)
            except ValueError as e:
                raise ValueError(f"{path}: not JSON ({e})") from None
        try:
            return FaultPlan.from_json(data)
        except ValueError as e:
            raise ValueError(f"{path}: {e}") from None

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
            f.write("\n")
        return path


def lint_plan(plan: FaultPlan) -> List[str]:
    """Problems a schema-valid plan can still have (chaos_tool lint):
    site patterns that match no instrumented site, rules shadowed into
    dead code, corrupt rules on payload-free sites."""
    problems: List[str] = []
    for i, rule in enumerate(plan.rules):
        matched = [s for s in SITES if fnmatch.fnmatchcase(s, rule.site)]
        if not matched:
            problems.append(
                f"rule {i}: site {rule.site!r} matches no instrumented "
                f"site (known: {', '.join(SITES)})")
        if rule.max_hits == 0:
            problems.append(f"rule {i}: max_hits=0 never fires")
        if rule.kind == "corrupt" and matched and not any(
                s in PAYLOAD_SITES for s in matched):
            problems.append(
                f"rule {i}: corrupt at {matched} has no payload to flip "
                f"(raises CorruptPayload without mutating anything)")
        if rule.kind == "corrupt_silent" and matched and not any(
                s in PAYLOAD_SITES for s in matched):
            problems.append(
                f"rule {i}: corrupt_silent at {matched} has no payload "
                f"to flip — the rule is a total no-op (payload sites: "
                f"{', '.join(PAYLOAD_SITES)})")
        if rule.kind == "torn" and matched and "ckpt.write" not in matched:
            problems.append(
                f"rule {i}: torn at {matched} has no staged file write "
                f"to truncate (only ckpt.write models a crash "
                f"mid-checkpoint-write)")
        if rule.kind == "stall" and float(rule.delay_s) > 0:
            problems.append(
                f"rule {i}: stall ignores delay_s={rule.delay_s!r} — "
                f"the hold is indefinite by definition (use kind "
                f"'delay' for a bounded slowdown)")
        if rule.kind == "partition":
            if matched and not all(s in BOARD_SITES for s in matched):
                problems.append(
                    f"rule {i}: partition at {matched} — the visibility "
                    f"mask only exists on the membership board (sites: "
                    f"{', '.join(BOARD_SITES)})")
            if float(rule.delay_s) > 0 or float(rule.prob) < 1.0 \
                    or rule.max_hits != 1:
                problems.append(
                    f"rule {i}: partition ignores prob/max_hits/delay_s "
                    f"— the mask is a standing window [after, "
                    f"heal_after) in gang steps, not an arrival-fired "
                    f"fault")
        elif int(rule.heal_after) != -1:
            problems.append(
                f"rule {i}: heal_after is only meaningful on kind "
                f"'partition' (this rule heals via max_hits)")
    return problems


def corrupt_buffer(buf, seed: int, hit: int) -> None:
    """Flip one bit per 64 bytes of a writable numpy buffer, seeded by
    the schedule draw so the corruption itself is deterministic.  No-op
    for payload-free sites (``buf is None``)."""
    if buf is None:
        return
    import numpy as np  # local: keep the module import dependency-free

    flags = getattr(buf, "flags", None)
    if flags is None or not flags.writeable:
        return  # broadcast views etc. — the raise still happens
    try:
        flat = buf.view(np.uint8).reshape(-1)
    except (ValueError, AttributeError):
        return  # non-contiguous / exotic layout: raise-only corrupt
    if flat.size == 0:
        return
    rng = int(decision(seed, "corrupt", hit) * (1 << 32))
    # Vectorized: a multi-GB staged payload must corrupt in one numpy
    # pass, not millions of Python-level element stores.
    offs = rng + np.arange(0, flat.size, 64, dtype=np.int64)
    np.bitwise_xor.at(flat, offs % flat.size,
                      np.left_shift(1, (offs % 8)).astype(np.uint8))

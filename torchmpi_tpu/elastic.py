"""Elastic gang resize: continue training at N-1 on peer death, re-admit
healed peers at step boundaries (docs/ELASTIC.md).

The reference's communicators were disposable — the gang could be torn
down and re-formed (PAPER.md) — but an MPI rank failure still aborted
the job.  This module closes that gap for the modern stack: when a
member of the training gang dies, the survivors agree on a new
membership view (a bounded two-phase reconcile over the host-staged
board, :mod:`torchmpi_tpu.faults.membership`), re-form the world mesh
at N-1 (:func:`runtime.resize_world` — the config-epoch bump strands
every cached :class:`~torchmpi_tpu.planner.CollectivePlan`), restore
the last fsync-verified checkpoint, deterministically re-partition the
state onto the survivors (ZeRO shard layouts and PS shard extents are
pure functions of ``(tree, n)``, so re-sharding is a rebuild, not a
migration), and resume the step loop.  A healed peer polls the board
(:func:`admit`) and rejoins only at a step boundary via the same
reconcile, restoring the original partition layout.

Membership granularity: one member per **process** on a multi-process
gang (the deployment shape), one member per **device** on the
single-process CPU sim (``members``/``world_size`` let tests carve an
8-device sim into any gang) — elasticity is fully testable without
hardware, driven by deterministic chaos plans on the new
``elastic.member`` fault site (``scripts/chaos_tool.py gen --shrink``).

Off by default and **never imported when off** — the
``analysis``/``obs``/``faults`` import discipline: ``Config.elastic``
is a consent gate for this driver layer, the dispatch path has no
branch on it anywhere, and ``import torchmpi_tpu`` never imports this
module (``tests/test_elastic.py`` asserts both).  Telemetry
(``tm_elastic_{reconcile,shrink,rejoin,quorum_lost,parked,fenced,
healed}_total`` + flight events) rides :mod:`torchmpi_tpu.obs`
through ``sys.modules`` when obs is active.

Partitions (docs/ELASTIC.md "Partitions and split-brain"):
``Config.elastic_quorum="majority"`` gates every reconcile and
recovery agreement on a strict majority of the last committed view —
a partitioned minority raises the typed
:class:`~torchmpi_tpu.faults.membership.QuorumLost` and
:func:`run_elastic` PARKS it (:func:`_park`: heartbeat-visible wait,
watchdog lease ``state="parked"``) until it can adopt, readmit into,
or retry against the healed board; epoch fencing
(``faults/fencing.py``) rides the same opt-in so a zombie minority's
board writes and checkpoint saves never land on the majority's
lineage.  Quorum off keeps the historical COMMIT semantics (a
partition can fork the view) and never imports either module; the
board-heartbeat staleness DETECTOR in :meth:`ElasticGang.poll` is
evidence shared by both modes — like the watchdog lease scan, it
names who looks dead, while quorum alone governs what may commit.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from . import runtime
from .faults import membership
from .faults.membership import MembershipView  # noqa: F401 (re-export)
from .utils import checkpoint, restart

PyTree = Any

# ``build(mesh, view) -> (init_fn, step_fn)``: the per-view step
# factory run_elastic rebuilds the training program through after every
# membership change.  ``init_fn() -> state`` returns the FULL
# (topology-portable) train state — the checkpoint template; sharded
# layouts (ZeRO partitions, PS shards, EF residuals) are derived from
# it under the view's mesh, which is what makes the re-partition
# deterministic.  ``step_fn(state, i) -> state``.
BuildFn = Callable[[Any, MembershipView], Tuple[Callable[[], PyTree],
                                                Callable[[PyTree, int],
                                                         PyTree]]]


class MemberDeath(RuntimeError):
    """A gang member died.  Raised out of :func:`run_elastic` only when
    the dead member is THIS process (the survivors continue without
    it); carries ``member`` (rank) and ``step``."""

    def __init__(self, member: int, step: int, msg: str = ""):
        super().__init__(
            msg or f"gang member {member} died at step {step}")
        self.member = int(member)
        self.step = int(step)


def _require_on():
    """Every public entry point's consent gate (the user must opt in
    via ``Config.elastic`` — same posture as the other layers' modes,
    minus any dispatch-path branch)."""
    cfg = runtime.effective_config()
    if cfg.elastic == "off":
        raise RuntimeError(
            "torchmpi_tpu.elastic requires Config.elastic='on' (or "
            "TORCHMPI_TPU_ELASTIC=1) — the elastic gang driver is "
            "opt-in; see docs/ELASTIC.md")
    return cfg


def _obs_record(event: str, *, epoch: int = 0, members: int = 0,
                peer: str = "") -> None:
    """tm_elastic_* through obs when active (sys.modules lookup — the
    driver never imports the telemetry it reports to)."""
    mod = sys.modules.get("torchmpi_tpu.obs")
    try:
        if mod is not None and mod.active():
            mod.record_elastic(event, epoch=epoch, members=members,
                               peer=peer)
    except Exception:  # noqa: BLE001 — telemetry never fails a resize
        pass


def _faults_mod():
    """The armed fault layer, or None (one string compare + sys.modules
    — matches the call-site discipline everywhere else)."""
    if runtime.effective_config().faults == "off":
        return None
    mod = sys.modules.get("torchmpi_tpu.faults")
    if mod is not None and mod.active():
        return mod
    return None


def _hotstate_mod():
    """The armed hot-state tier, or None (docs/HOTSTATE.md) — the same
    sys.modules seam: a session that never enabled
    ``torchmpi_tpu.hotstate`` never imports it here."""
    mod = sys.modules.get("torchmpi_tpu.hotstate")
    if mod is not None and mod.active():
        return mod
    return None


def _hotstate_publish(gang: "ElasticGang", state: PyTree,
                      step: int) -> None:
    """Stream this rank's post-step state to its buddy's RAM when the
    hot tier is armed.  A ``FencedWriterError`` propagates on purpose
    (a fenced stream IS the zombie-minority signal and takes the same
    park path as a fenced board write); everything else in the tier is
    already best-effort."""
    mod = _hotstate_mod()
    if mod is not None:
        mod.replicator().publish(
            state, step, rank=gang._rank,
            epoch=getattr(gang.view, "epoch", 0))


def _hotstate_note_shrink(ranks: Sequence[int], step: int) -> None:
    """Membership evidence for the hot tier: the dead ranks stop
    streaming, but their REPLICAS must stay — they are exactly what the
    RAM rung restores from on the shrink recovery."""
    mod = _hotstate_mod()
    if mod is not None:
        try:
            mod.replicator().note_shrink(ranks, step)
        except Exception:  # noqa: BLE001 — bookkeeping, not correctness
            pass


def _member_peer(m: int) -> str:
    """Ledger peer name for gang member ``m`` (prefixed so member rows
    never collide with PS ``host:port`` endpoints)."""
    return f"member:{int(m)}"


def _is_hang(e: BaseException) -> bool:
    """Is ``e`` the watchdog's ``CollectiveHangError``?  sys.modules
    check (the restart.py discipline): the error can only exist if the
    watchdog raised it, so the module is necessarily loaded then."""
    mod = sys.modules.get("torchmpi_tpu.watchdog")
    return mod is not None and isinstance(e, mod.CollectiveHangError)


class ElasticGang:
    """Membership state + resize mechanics for one training gang.

    ``directory`` is the checkpoint directory; the membership board
    defaults to ``Config.elastic_dir`` or ``<directory>/membership``.
    ``members`` (default: one per process, or one per device on the
    single-process sim) are integer ranks; on the sim, ``world_size``
    fixes the member -> device mapping (member ``m`` owns the
    ``len(devices)/world_size`` devices starting at slot ``m``) so a
    survivors-only gang maps to the SAME devices a full gang would give
    them — the bit-reproducibility anchor of the shrink tests.
    """

    def __init__(self, directory: str, *,
                 members: Optional[Sequence[int]] = None,
                 world_size: Optional[int] = None,
                 board_dir: Optional[str] = None,
                 local: Optional[Sequence[int]] = None):
        cfg = _require_on()
        self.poll_s = float(cfg.elastic_poll_s)
        self.deadline_s = float(cfg.elastic_deadline_s)
        # Quorum gating (docs/ELASTIC.md "Partitions and split-brain"):
        # one string compare; "off" keeps the historical semantics and
        # never imports the fencing module.
        self.quorum = cfg.elastic_quorum == "majority"
        self._multiproc = jax.process_count() > 1
        all_devs = list(jax.devices())
        if members is None:
            members = (range(jax.process_count()) if self._multiproc
                       else range(len(all_devs)))
        members = tuple(sorted(int(m) for m in members))
        # ``local``: the members THIS process speaks for — None keeps
        # the historical granularity (its own rank on a multi-process
        # gang; every member on the single-process sim).  A sim gang
        # speaking for a SUBSET is the protocol harness the partition
        # tests run two independent processes over one board with
        # (each side trains its own devices; only the BOARD is shared,
        # which is exactly a partition's failure surface).
        self._local_subset = local is not None
        if local is not None:
            if self._multiproc:
                raise ValueError(
                    "local= is a single-process (protocol-harness) "
                    "knob; a multi-process gang speaks for its own "
                    "rank")
            local = tuple(sorted(int(r) for r in local))
            if not local or not set(local) <= set(members):
                raise ValueError(
                    f"local {list(local)} must be a non-empty subset "
                    f"of members {list(members)}")
        # The board reads as THIS process's rank (the partition
        # visibility mask is per reader; on the sim — where one process
        # speaks for every member — the lowest spoken-for member is
        # the reader, so a one-way mask can model exactly what each
        # side of a split board would see).
        self._rank = (jax.process_index() if self._multiproc
                      else int((local or members)[0]))
        self.board = membership.Board(
            board_dir or cfg.elastic_dir
            or os.path.join(directory, "membership"),
            reader_rank=self._rank)
        # Lease-death floor: only leases renewed AFTER this driver
        # started count as evidence — a SIGKILLed previous run's
        # leftover leases on the persistent board must not shrink a
        # slow-starting peer out of the new gang (docs/WATCHDOG.md).
        import time as _time

        self._lease_floor = _time.time()
        if cfg.watchdog != "off":
            # Adopt this board as the watchdog's lease home when
            # watchdog_dir was left unset (docs/WATCHDOG.md layer 2:
            # the leases belong on the membership board, but its
            # default location — <ckpt dir>/membership — is only known
            # HERE, not at runtime.init).  An explicitly configured
            # lease dir wins; the lease-death scan in poll() reads
            # wherever the watchdog actually leases.
            from . import watchdog

            if watchdog.active() and watchdog.lease_dir() is None:
                watchdog.set_lease_dir(self.board.directory)
        # The member -> devices map covers EVERY possible member slot,
        # not just the starting set: a driver restarted with only the
        # survivors must still be able to admit a healed rank it never
        # met (the rank's devices are a function of its slot, not of
        # who happened to be alive at startup).
        if self._multiproc:
            ws = jax.process_count()
            self._dev_of = {
                m: [d for d in all_devs if d.process_index == m]
                for m in range(ws)}
            self.local_ranks: Tuple[int, ...] = (jax.process_index(),)
        else:
            ws = int(world_size) if world_size else (members[-1] + 1)
            if ws < members[-1] + 1 or len(all_devs) % ws:
                raise ValueError(
                    f"world_size {ws} must cover member {members[-1]} "
                    f"and divide the device count {len(all_devs)}")
            per = len(all_devs) // ws
            self._dev_of = {m: all_devs[m * per:(m + 1) * per]
                            for m in range(ws)}
            self.local_ranks = local if local is not None else members
        for m, devs in self._dev_of.items():
            if not devs:
                raise ValueError(f"member {m} owns no devices")
        # Adopt the board's committed view: the WHOLE view when its
        # member set matches the caller's (a healed joiner re-entering
        # after `admit` must hold the SAME (epoch, step) the survivors
        # committed, so their recovery-agreement tags line up), else
        # just its epoch (the caller's ``members`` is the operator's
        # statement of who is starting NOW; proposing above the
        # history avoids colliding with a past epoch's commit files).
        committed = self.board.committed_view()
        if committed is not None and committed.members == members:
            self.view = committed
        else:
            epoch0 = committed.epoch if committed is not None else 0
            self.view = MembershipView(epoch=epoch0, members=members,
                                       step=0)
        self.stats = {"shrinks": 0, "rejoins": 0, "reconciles": 0}
        # The incarnation each member is CURRENTLY admitted at (the
        # board's counter at adoption; docs/ELASTIC.md): a join request
        # carrying a HIGHER incarnation for a sitting member is a new
        # life of a rank whose previous death was never committed — the
        # join itself is the death notice, and poll() shrinks first
        # instead of admitting an ambiguous joiner.  A join already
        # PENDING at the board's current counter when this driver
        # starts (rank died, admitted a new life, and the driver
        # restarted before seeing it) adopts the PRIOR incarnation: a
        # live sitting member never posts an incarnation-carrying join,
        # so the pending one must be a new life knocking (code review).
        joins = self.board.join_details()
        self._inc: Dict[int, int] = {}
        for m in self.view.members:
            inc = self.board.incarnation(m)
            j = int(joins.get(m, {}).get("incarnation", 0) or 0)
            self._inc[m] = min(inc, j - 1) if j >= max(1, inc) else inc
        # Recovery-agreement round counter: reset on every view change
        # so every participant — however it got here (survivor,
        # restarted driver, healed joiner) — derives the same tag
        # sequence for the same view.  Recoveries are collective
        # (restart.recover's contract), so the per-view counts advance
        # in lockstep.
        self._agree_round = 0
        self._last_hb = 0.0
        # A previous incarnation's in-flight protocol state must not
        # poison this one: drop our own agreement values and any
        # propose/commit files above the committed epoch (committed
        # history stays — committed_view reads it).
        for r in self.local_ranks:
            self.board.clear_values(r)
            self.board.clear_votes_above(r, self.view.epoch)
        # Board-heartbeat sightings (member -> newest ts this gang has
        # SEEN) — the partition detection signal: a member whose
        # heartbeat stops being visible/renewed relative to the
        # freshest member's goes stale (docs/ELASTIC.md).
        self._hb_seen: Dict[int, float] = {}
        # Epoch fencing rides the quorum opt-in: arm this process's
        # writer identity on the board (votes/heartbeats check it) and
        # publish it for the checkpoint-save seam.  Quorum off = the
        # module is never imported (tests assert it, subprocess-wise).
        if self.quorum:
            from .faults import fencing

            self._fence = fencing.arm(
                self.board, self._rank, epoch=self.view.epoch,
                incarnation=self._inc.get(self._rank, 0))
        else:
            self._fence = None

    # -- mesh ------------------------------------------------------------

    def member_mesh(self):
        """(Re-)form the world mesh over the current view's devices —
        1-D ``(ici,)`` for one device per member, ``(dcn=members,
        ici=per)`` otherwise.  Routes through
        :func:`runtime.resize_world`, so the config epoch bumps and
        every stale CollectivePlan is dropped."""
        devs = [d for m in self.view.members for d in self._dev_of[m]]
        per = len(self._dev_of[self.view.members[0]])
        shape = (None if per == 1
                 else {runtime.DCN_AXIS: len(self.view.members),
                       runtime.ICI_AXIS: per})
        return runtime.resize_world(devs, shape=shape)

    def participants(self) -> int:
        """Surviving PROCESS count (recovery-agreement granularity)."""
        if not self._multiproc:
            return 1
        return len(self.view.members)

    def agreement(self):
        """Survivors-only min-agreement callable for
        :func:`restart.recover` (the full-gang
        ``checkpoint.agree_min_step`` would hang on the dead peer).

        With quorum on, the same gate that stops a minority COMMITTING
        a view stops it AGREEING a restore step: a board whose
        committed epoch moved past this rank's view means a majority
        reconciled without us — agreeing among a minority would settle
        a step the majority's lineage never chose.  The typed
        :class:`~torchmpi_tpu.faults.membership.QuorumLost` routes the
        caller into the park/rejoin path."""

        def agree(value: int) -> int:
            if self.quorum:
                committed = self.board.committed_view()
                if committed is not None and \
                        committed.epoch > self.view.epoch:
                    raise membership.QuorumLost(
                        epoch=self.view.epoch,
                        voters=self.local_ranks,
                        quorum_of=committed.members,
                        msg=f"recovery agreement refused: the board "
                            f"committed epoch {committed.epoch} past "
                            f"this rank's view epoch "
                            f"{self.view.epoch} — a majority moved "
                            f"on; park and rejoin instead of agreeing "
                            f"a stale restore step")
            self._agree_round += 1
            tag = (f"e{self.view.epoch}s{self.view.step}"
                   f"r{self._agree_round}")
            return membership.agree_min(
                self.board, tag,
                self.local_ranks, self.view.members, value,
                deadline_s=self.deadline_s, poll_s=self.poll_s)

        return agree

    # -- step-boundary poll ----------------------------------------------

    def poll(self, step: int) -> Optional[Tuple[str, List[int]]]:
        """One step-boundary membership check; returns ``("shrink",
        dead_members)``, ``("rejoin", joiners)``, or None.

        With the fault layer armed this fires the ``elastic.member``
        chaos site once per member in rank order (arrival ordinal =
        ``step * len(members) + index`` — what ``chaos_tool gen
        --shrink`` computes): an injected hard ``fail`` kills that
        member outright; a transient ``drop`` records a ledger failure
        so repeated drops escalate healthy -> suspect -> dead through
        ``HealthLedger.decide`` exactly like any other peer."""
        import time

        # The board's gang-step clock: the deterministic window the
        # injected partition mask is evaluated against (a plain int
        # max, free when nothing is armed).
        self.board.note_step(step)
        # Heartbeats are liveness evidence at detection granularity
        # (~deadline), not per-step state: throttle the fsync'd board
        # writes off the hot step loop.
        now = time.monotonic()
        if now - self._last_hb >= max(self.poll_s, self.deadline_s / 4):
            for r in self.local_ranks:
                if r in self.view.members:
                    self.board.heartbeat(r, epoch=self.view.epoch,
                                         step=step)
            self._last_hb = now
        dead: set = set()
        # Board-heartbeat staleness (docs/ELASTIC.md "Partitions and
        # split-brain"): the evidence a partition actually produces is
        # a member's board files no longer being visible or renewed.
        # A member whose heartbeat this gang HAS seen before, but whose
        # newest sighting lags the freshest member heartbeat by more
        # than the detection deadline, is dead-or-partitioned-away.
        # Staleness is relative to the gang's freshest member — not
        # wall clock — so a whole-gang stall (compile, slow step) ages
        # every heartbeat together and trips nothing; a member never
        # seen at all is NOT evidence (absence proves nothing — the
        # slow-starter posture of the lease scan below).
        for m, d in self.board.heartbeats().items():
            if m in self._dev_of:
                self._hb_seen[m] = max(self._hb_seen.get(m, 0.0),
                                       float(d.get("ts", 0.0)))
        seen = {m: self._hb_seen[m] for m in self.view.members
                if m in self._hb_seen}
        if seen:
            newest = max(seen.values())
            dead |= {m for m, ts in seen.items()
                     if newest - ts > self.deadline_s}
        faults = _faults_mod()
        if faults is not None:
            led = faults.ledger()
            if faults.injecting():
                for m in self.view.members:
                    try:
                        faults.fire("elastic.member", peer=_member_peer(m))
                    except faults.InjectedFailure:
                        dead.add(m)
                    except faults.TransientFault:
                        led.record(_member_peer(m), ok=False)
                    except RuntimeError as e:
                        # A member liveness check the WATCHDOG had to
                        # break (an injected `stall` held it past the
                        # deadline — docs/WATCHDOG.md) is itself the
                        # death evidence: the gang wedged on exactly
                        # this member's boundary check.
                        if not _is_hang(e):
                            raise
                        dead.add(m)
                    else:
                        led.record(_member_peer(m), ok=True)
            dead |= {m for m in self.view.members
                     if led.decide(_member_peer(m)) == "raise"}
        if self._multiproc and \
                runtime.effective_config().watchdog != "off":
            # Lease-based liveness (docs/WATCHDOG.md layer 2): a member
            # whose watchdog lease EXPIRED — or carries the `escalated`
            # tombstone an unbreakable stall exits through — is PR-10
            # death evidence, folded into the same shrink verdict as an
            # injected kill or a ledger escalation.  Read from wherever
            # this process actually leases (every rank shares the
            # config, so that is where the peers lease too; the
            # constructor adopted the board when nothing was
            # configured).  One string compare when the watchdog is
            # off; a member that never leased is not evidence.
            from . import watchdog

            ld = watchdog.lease_dir()
            if ld is not None:
                dead |= {r for r in watchdog.dead_ranks(
                             ld, newer_than=self._lease_floor)
                         if r in self.view.members
                         and r not in self.local_ranks}
        details = self.board.join_details()
        # A join from a rank STILL in the view under a NEWER incarnation
        # is a twice-dead rank's fresh life (docs/ELASTIC.md): its
        # previous death was never committed, and the join is the death
        # notice — shrink the stale life out first; the next boundary's
        # poll then sees an ordinary healed-joiner request.
        for r, d in details.items():
            if r in self.view.members and \
                    int(d.get("incarnation", 0)) > self._inc.get(r, 0):
                dead.add(r)
        if dead:
            return ("shrink", sorted(dead))
        joins = [r for r in sorted(details)
                 if r not in self.view.members and r in self._dev_of
                 and self._joiner_alive(r)]
        if joins:
            return ("rejoin", joins)
        return None

    def _joiner_alive(self, rank: int) -> bool:
        """Admit only joiners that look alive: a join request whose
        poster is heartbeating (``admit()`` heartbeats while it polls)
        is a waiting peer; one whose heartbeat went stale is a joiner
        that crashed AFTER requesting — growing the mesh toward it
        would wedge the gang's first collective.  A join with NO
        heartbeat at all is an operator's explicit request and is
        trusted."""
        import time

        hb = self.board.heartbeats().get(int(rank))
        if hb is None:
            return True
        return time.time() - float(hb.get("ts", 0)) <= self.deadline_s

    def includes_self(self, ranks: Sequence[int]) -> bool:
        """Is THIS process among ``ranks``?  On the full sim every
        member is local and a death is by definition a peer's; a
        subset-harness gang (``local=``) dies when any rank it speaks
        for does."""
        if self._multiproc:
            return jax.process_index() in set(ranks)
        if self._local_subset:
            return bool(set(ranks) & set(self.local_ranks))
        return False

    # -- resize ----------------------------------------------------------

    def _reconcile(self, members: Sequence[int], *, step: int,
                   voters: Optional[Sequence[int]] = None
                   ) -> MembershipView:
        view = membership.reconcile(
            self.board, self.local_ranks, members,
            epoch=self.view.epoch + 1, step=step, voters=voters,
            quorum_of=self.view.members if self.quorum else None,
            deadline_s=self.deadline_s, poll_s=self.poll_s)
        self.stats["reconciles"] += 1
        _obs_record("reconcile", epoch=view.epoch,
                    members=len(view.members))
        self.view = view
        self._agree_round = 0  # new view => fresh, lockstep tag sequence
        if self._fence is not None:
            self._fence.update(view.epoch)
        return view

    def adopt(self, view: MembershipView) -> None:
        """Adopt a view committed WITHOUT this rank's vote — the park
        loop's exit (the majority committed while we were quorum-lost,
        or :func:`admit` returned the grown view readmitting us).
        Resets the agreement-round lockstep, clears this rank's stale
        protocol state above the adopted epoch, refreshes the admitted
        incarnations, and moves the fence forward so our writes land
        again."""
        self.view = view
        self._agree_round = 0
        self._hb_seen.clear()  # old sightings are pre-heal evidence
        for r in self.local_ranks:
            self.board.clear_values(r)
            self.board.clear_votes_above(r, view.epoch)
        for m in view.members:
            self._inc[m] = self.board.incarnation(m)
        if self._fence is not None:
            self._fence.update(
                view.epoch,
                incarnation=self._inc.get(self._rank, 0))

    def shrink(self, dead: Sequence[int], *, step: int):
        """Agree on the survivors-only view and re-form the mesh at
        N-1 (or N-k).  Returns the new mesh; the caller then recovers
        state from the last checkpoint and rebuilds its step."""
        dead = sorted(set(int(m) for m in dead))
        survivors = [m for m in self.view.members if m not in dead]
        if not survivors:
            raise membership.MembershipError(
                f"every member died at step {step} — nothing to "
                f"shrink to")
        faults = _faults_mod()
        if faults is not None:
            led = faults.ledger()
            for m in dead:
                # The gang decision IS the death verdict — pin the
                # ledger so a later decide() agrees with the view.
                for _ in range(led.dead_after):
                    led.record(_member_peer(m), ok=False)
        view = self._reconcile(survivors, step=step)
        self.stats["shrinks"] += 1
        _obs_record("shrink", epoch=view.epoch, members=len(view.members),
                    peer=",".join(_member_peer(m) for m in dead))
        return self.member_mesh()

    def grow(self, joiners: Sequence[int], *, step: int):
        """Re-admit healed members at a step boundary: the CURRENT
        members vote the grown view in (the joiner polls it via
        :func:`admit`), the mesh re-forms at the original size, and
        the original partition layout is restored by the same
        deterministic re-partition that shrank it.  The caller must
        have checkpointed ``step`` BEFORE growing — the joiner restores
        exactly that step."""
        joiners = sorted(set(int(r) for r in joiners)
                         - set(self.view.members))
        voters = list(self.view.members)
        view = self._reconcile(sorted(set(voters) | set(joiners)),
                               step=step, voters=voters)
        faults = _faults_mod()
        for r in joiners:
            self.board.clear_join(r)
            # Adopt the life being admitted: later joins at the same
            # incarnation are this life re-knocking, a HIGHER one is
            # the next death notice.
            self._inc[r] = self.board.incarnation(r)
            if faults is not None:
                # A re-admitted member starts with a clean bill —
                # its pre-death failure streak is stale evidence.
                faults.ledger().record(_member_peer(r), ok=True)
        self.stats["rejoins"] += 1
        _obs_record("rejoin", epoch=view.epoch, members=len(view.members),
                    peer=",".join(_member_peer(r) for r in joiners))
        return self.member_mesh()


def _seed_joiner_checkpoints(directory: str, step: int,
                             joiners: Sequence[int],
                             gang: ElasticGang) -> None:
    """Give each joiner a per-process checkpoint file for the rejoin
    boundary: ``checkpoint.save`` writes ``ckpt_<step>_p<proc>.npz``
    for the CALLING process only, and recovery reads only a process's
    own files — without this the joiner's newest checkpoint predates
    its death and the post-grow min-agreement would roll the whole
    gang back to it.  The state is replicated by the ``build``
    contract (full/topology-portable leaves, identical on every
    process), so the lowest surviving member's file IS the joiner's
    file — seeded via ``checkpoint.replicate_for`` (tmp + atomic
    rename, the checkpoint discipline; with ``Config.ckpt_redundancy``
    on the source bytes are digest-verified first — repairing from a
    buddy copy if the survivor's own primary rotted — and each joiner
    gets the stamped metadata plus its own buddy mirrors,
    docs/CHECKPOINT.md).  No-op on the single-process sim (one
    process, one file)."""
    if not gang._multiproc or \
            jax.process_index() != min(gang.view.members):
        return
    checkpoint.replicate_for(directory, step, [int(r) for r in joiners])


def _is_fenced(e: BaseException) -> bool:
    """Is ``e`` the fencing layer's ``FencedWriterError``?  sys.modules
    check (the restart.py discipline): the error can only exist if the
    fencing module raised it, so it is necessarily loaded then."""
    mod = sys.modules.get("torchmpi_tpu.faults.fencing")
    return mod is not None and isinstance(e, mod.FencedWriterError)


def _park(gang: ElasticGang, directory: str, *, step: int,
          suspects: Sequence[int], cause: BaseException,
          budget_s: float) -> str:
    """The minority side of a quorum loss (docs/ELASTIC.md "Partitions
    and split-brain"): instead of committing a forked view — or dying
    and demanding an operator restart — the rank PARKS: a bounded,
    heartbeat-visible wait loop that keeps the rank alive and
    observable (board heartbeats with the no-view-claimed epoch -1;
    watchdog lease state ``parked`` naming the epoch it waits on, so
    ``obs_tool blame --live`` does not misread it as a corpse) while it
    re-polls the board for one of three exits:

    - ``"adopted"``  — the majority committed a higher-epoch view that
      STILL CONTAINS this rank (it was partitioned, not dropped):
      adopt it and resume at its boundary.
    - ``"admitted"`` — the majority committed past us WITHOUT us: run
      the healed-peer path in place (:func:`admit` — incarnation bump,
      join request, wait for the grown view), adopt the admitting
      view.  No process restart.
    - ``"retry"``    — nobody committed anything (BOTH sides of the
      split were minorities — e.g. a three-way partition) and every
      suspect is heartbeating fresh again: the partition healed, so
      re-enter the driver loop and reconcile with full visibility.

    Exhausting ``budget_s`` re-raises ``cause`` (the original
    ``QuorumLost``/``FencedWriterError``) — a partition that never
    heals must eventually surface, not wait forever."""
    import time

    _obs_record("quorum_lost", epoch=gang.view.epoch,
                members=len(gang.view.members),
                peer=",".join(_member_peer(m) for m in suspects))
    _obs_record("parked", epoch=gang.view.epoch,
                members=len(gang.view.members))
    wd = sys.modules.get("torchmpi_tpu.watchdog")
    if wd is not None and wd.active():
        wd.set_state("parked",
                     detail=f"waiting for a committed epoch > "
                            f"{gang.view.epoch}")
    t0 = time.monotonic()
    t_park = time.time()
    try:
        while True:
            for r in gang.local_ranks:
                # The waiting beacon: epoch -1 claims no view, so it is
                # fence-exempt and keeps the rank joiner-alive.
                gang.board.heartbeat(r, epoch=-1, step=step)
            committed = gang.board.committed_view()
            if committed is not None and \
                    committed.epoch > gang.view.epoch:
                if all(r in committed.members for r in gang.local_ranks):
                    gang.adopt(committed)
                    _obs_record("healed", epoch=committed.epoch,
                                members=len(committed.members))
                    return "adopted"
                if gang._multiproc or len(gang.local_ranks) == 1:
                    remaining = max(gang.poll_s,
                                    budget_s - (time.monotonic() - t0))
                    view = admit(directory, gang._rank,
                                 board_dir=gang.board.directory,
                                 deadline_s=remaining,
                                 poll_s=gang.poll_s)
                    gang.adopt(view)
                    _obs_record("healed", epoch=view.epoch,
                                members=len(view.members))
                    return "admitted"
                raise cause  # full sim: a committed view excluding
                #              every local member is unrecoverable
                #              in-process
            if suspects:
                hbs = gang.board.heartbeats()
                if all(float(hbs.get(m, {}).get("ts", 0)) > t_park
                       for m in suspects):
                    # Every rank we timed out on is fresh again and
                    # nobody committed past us: the partition healed
                    # with no majority formed — reconcile over again
                    # with full visibility.
                    _obs_record("healed", epoch=gang.view.epoch,
                                members=len(gang.view.members))
                    gang._hb_seen.clear()
                    return "retry"
            if time.monotonic() - t0 > budget_s:
                raise cause
            time.sleep(gang.poll_s)
    finally:
        if wd is not None and wd.active():
            wd.set_state("running")


def _member_of_failure(e: BaseException) -> Optional[int]:
    """Map a fault-layer error to the gang member it implicates, if
    any: a ``PeerTimeoutError`` — or a watchdog ``CollectiveHangError``
    (a mid-step stall the watchdog broke) — whose peer is a
    ``member:<rank>`` row.  Checked via sys.modules (the restart.py
    discipline)."""
    mod = sys.modules.get("torchmpi_tpu.faults.policy")
    timeoutish = (mod is not None
                  and isinstance(e, mod.PeerTimeoutError)) or _is_hang(e)
    if not timeoutish:
        return None
    peer = str(getattr(e, "peer", ""))
    if peer.startswith("member:") and peer[len("member:"):].isdigit():
        return int(peer[len("member:"):])
    return None


def run_elastic(build: BuildFn, *, steps: int, directory: str,
                save_every: int = 10, max_restarts: int = 3,
                members: Optional[Sequence[int]] = None,
                world_size: Optional[int] = None,
                gang: Optional[ElasticGang] = None,
                park_budget_s: Optional[float] = None
                ) -> Tuple[PyTree, Dict[str, Any]]:
    """Run ``steps`` steps elastically: the detect -> shrink ->
    rebalance -> rejoin loop over :func:`restart.run_with_restarts`'s
    checkpoint machinery.

    ``build(mesh, view)`` returns ``(init_fn, step_fn)`` for one
    membership view (see :data:`BuildFn`); it is re-invoked after every
    membership change, which is where the deterministic re-partition
    happens — ZeRO shard layouts, PS shard extents and EF residual
    buckets are pure functions of ``(state tree, view)``, so rebuilding
    them from the recovered full state IS the rebalance.

    Per-epoch segment: recover the newest fsync-verified checkpoint
    (survivors-only agreement on a multi-process gang), then step,
    checkpointing every ``save_every`` steps.  At every step boundary
    the gang polls membership (:meth:`ElasticGang.poll`):

    - a dead peer (injected hard-fail at the ``elastic.member`` site,
      ledger escalation to ``dead``, or a ``PeerTimeoutError``
      implicating a member mid-step) triggers :meth:`~ElasticGang.
      shrink` and the segment restarts at N-1 from the last
      checkpoint — no operator intervention;
    - if THIS process is the dead member, :class:`MemberDeath` raises
      out (finish dying, then come back through :func:`admit`);
    - a posted join request triggers a checkpoint at the boundary and
      :meth:`~ElasticGang.grow` — the healed member restores exactly
      that step and the original layout is back.

    Non-membership failures take the plain restore-and-replay path
    with the ``max_restarts`` budget, exactly like
    ``run_with_restarts``.  Under ``Config.elastic_quorum="majority"``
    a quorum loss (a partition left this side a minority — typed
    ``QuorumLost``, or a write FENCED by a majority that moved on)
    PARKS instead of committing or dying (:func:`_park`): the rank
    waits heartbeat-visible up to ``park_budget_s`` (default 10x the
    reconcile deadline) and rejoins the majority's committed epoch in
    place once the partition heals — counted in ``info["parks"]`` and
    bounded by ``max_restarts`` parks before the cause re-raises.
    Returns ``(state, info)`` with ``info`` carrying ``shrinks``/
    ``rejoins``/``reconciles``/``parks``/``restarts_used``/
    ``recovered_step``/``recoveries`` (every step a recovery settled
    on, in order — the view-schedule evidence)/``steps_run`` and the
    final ``view``.
    """
    cfg = _require_on()
    if steps < 0:
        raise ValueError(f"steps must be >= 0, got {steps}")
    if gang is None:
        gang = ElasticGang(directory, members=members,
                           world_size=world_size)
    park_budget = (10.0 * cfg.elastic_deadline_s
                   if park_budget_s is None else float(park_budget_s))
    restarts = 0
    parks = 0
    steps_run = 0
    recovered_step = 0
    recoveries: List[int] = []  # every step a recovery settled on, in
    #                             order — the view-schedule evidence the
    #                             partition acceptance replays
    mesh = None  # carried from shrink()/grow(): ONE resize per change

    def quorum_park(e: BaseException, step: int,
                    suspects: Sequence[int]) -> str:
        nonlocal parks
        parks += 1
        if parks > max_restarts:
            raise e
        return _park(gang, directory, step=step, suspects=suspects,
                     cause=e, budget_s=park_budget)

    while True:
        if mesh is None:
            mesh = gang.member_mesh()
        init_fn, step_fn = build(mesh, gang.view)
        template = init_fn()
        try:
            state, i = restart.recover(
                init_fn, directory, template,
                participants=gang.participants(),
                agree=gang.agreement())
        except membership.QuorumLost as e:
            # The agreement gate: a majority committed past this view
            # while we were down/partitioned — park, adopt/admit, and
            # rebuild against the adopted view.
            if quorum_park(e, recovered_step, []) != "retry":
                mesh = None
            continue
        recovered_step = i
        recoveries.append(i)
        resized = False
        while i < steps:
            try:
                ev = gang.poll(i)
            except BaseException as e:  # noqa: BLE001 — classified below
                if not (isinstance(e, membership.QuorumLost)
                        or _is_fenced(e)):
                    raise
                # A FENCED boundary heartbeat: the board committed past
                # this rank's view while it was partitioned away — the
                # zombie-minority signal; park and rejoin.
                if quorum_park(e, i, []) != "retry":
                    mesh = None
                resized = True
                break
            if ev is not None:
                kind, ranks = ev
                if kind == "shrink":
                    if gang.includes_self(ranks):
                        raise MemberDeath(gang._rank, i)
                    try:
                        mesh = gang.shrink(ranks, step=i)
                        _hotstate_note_shrink(ranks, i)
                    except membership.QuorumLost as e:
                        # The suspects are a majority of the view: WE
                        # are the partitioned minority — park instead
                        # of committing a forked survivor view.
                        if quorum_park(e, i, ranks) != "retry":
                            mesh = None
                else:
                    # Rejoin happens at a SAVED boundary so the healed
                    # member restores exactly this step.  The same
                    # quorum guard as the shrink sites: a partition
                    # landing mid-grow can fence the boundary save or
                    # shrink the grow reconcile's voters below quorum
                    # — park, don't crash the driver (review).
                    try:
                        checkpoint.save(directory, state, step=i)
                        _seed_joiner_checkpoints(directory, i, ranks,
                                                 gang)
                        mesh = gang.grow(ranks, step=i)
                    except BaseException as e:  # noqa: BLE001
                        if not (isinstance(e, membership.QuorumLost)
                                or _is_fenced(e)):
                            raise
                        if quorum_park(e, i, []) != "retry":
                            mesh = None
                resized = True
                break
            try:
                state = step_fn(state, i)
                steps_run += 1
                i += 1
                # The hot tier streams EVERY completed step (the disk
                # tier below saves every ``save_every``) — that gap is
                # exactly the replay the RAM rung erases on recovery.
                _hotstate_publish(gang, state, i)
                if i % save_every == 0 or i == steps:
                    checkpoint.save(directory, state, step=i)
            except KeyboardInterrupt:
                raise
            except BaseException as e:  # noqa: BLE001 — the elastic
                # loop IS the handler: shrink, restore, or re-raise.
                if isinstance(e, membership.QuorumLost) or _is_fenced(e):
                    # A fenced write (or an in-step quorum loss) means
                    # the majority's lineage moved past this rank —
                    # the zombie-minority case; park and rejoin it.
                    if quorum_park(e, i, []) != "retry":
                        mesh = None
                    resized = True
                    break
                member = _member_of_failure(e)
                if member is not None and member in gang.view.members:
                    if gang.includes_self([member]):
                        raise MemberDeath(member, i) from e
                    try:
                        mesh = gang.shrink([member], step=i)
                        _hotstate_note_shrink([member], i)
                    except membership.QuorumLost as qe:
                        if quorum_park(qe, i, [member]) != "retry":
                            mesh = None
                    resized = True
                    break
                restarts += 1
                if restarts > max_restarts:
                    raise
                # Plain (non-membership) restore: the view — and with
                # it the mesh, the step program, and every cached
                # CollectivePlan — is unchanged; recover in place
                # instead of tearing the segment down and re-jitting.
                try:
                    state, i = restart.recover(
                        init_fn, directory, template,
                        participants=gang.participants(),
                        agree=gang.agreement())
                except membership.QuorumLost as qe:
                    if quorum_park(qe, i, []) != "retry":
                        mesh = None
                    resized = True
                    break
                recovered_step = i
                recoveries.append(i)
        if not resized:
            return state, {"shrinks": gang.stats["shrinks"],
                           "rejoins": gang.stats["rejoins"],
                           "reconciles": gang.stats["reconciles"],
                           "restarts": restarts,
                           "restarts_used": restarts,
                           "parks": parks,
                           "recoveries": list(recoveries),
                           "steps_run": steps_run,
                           "recovered_step": recovered_step,
                           "view": gang.view}


def admit(directory: str, rank: int, *,
          board_dir: Optional[str] = None,
          deadline_s: Optional[float] = None,
          poll_s: Optional[float] = None) -> MembershipView:
    """The healed peer's half of a rejoin: bump this rank's per-life
    **incarnation id** on the board, post a join request carrying it,
    and poll until a committed view containing ``rank`` appears — the
    gang admits at its next step boundary, so the returned
    ``view.step`` is the checkpoint step to restore (the caller then
    re-enters :func:`run_elastic` with the full member set).  Blocks up
    to ``deadline_s`` (default ``Config.elastic_deadline_s``).

    The incarnation bump is what makes a *twice-dead* rank safe
    (docs/ELASTIC.md — the old stale-view-admission caveat, resolved):
    every committed view at or below the epoch current when this life
    started is treated as stale — even one that still lists ``rank``
    because the survivors have not committed its previous death yet —
    so admit only ever returns a view the gang committed AFTER seeing
    this life's join.  The gang side (``ElasticGang.poll``) reads the
    higher incarnation on a sitting member's join as the death notice
    and shrinks the stale life out first."""
    import time

    cfg = _require_on()
    board = membership.Board(
        board_dir or cfg.elastic_dir
        or os.path.join(directory, "membership"),
        reader_rank=int(rank))
    deadline_s = (cfg.elastic_deadline_s if deadline_s is None
                  else float(deadline_s))
    poll_s = cfg.elastic_poll_s if poll_s is None else float(poll_s)
    inc = board.bump_incarnation(rank)
    view = board.committed_view()
    min_epoch = (view.epoch + 1) if view is not None else 0
    board.request_join(rank, incarnation=inc)
    t0 = time.monotonic()
    while True:
        # Heartbeat while waiting: the gang admits only joiners that
        # look ALIVE (a stale-heartbeat join is a joiner that crashed
        # after requesting — growing toward it would wedge the gang).
        board.heartbeat(rank, epoch=-1, step=-1, incarnation=inc)
        view = board.committed_view()
        if view is not None and view.epoch >= min_epoch \
                and int(rank) in view.members:
            return view
        if time.monotonic() - t0 > deadline_s:
            raise membership.ReconcileTimeout(
                f"no committed view containing rank {rank} appeared "
                f"within {deadline_s:.3g}s")
        time.sleep(poll_s)


# ---------------------------------------------------------------------------
# Deterministic re-partition helpers (the "rebalance" of the loop).
# ---------------------------------------------------------------------------


def rebucket_ef_residuals(residuals, params_template: PyTree,
                          old_shape: Tuple[int, int], *,
                          axis_names=None, mesh=None,
                          n_buckets: Optional[int] = None) -> list:
    """Re-bucket DCN error-feedback residual state
    (``gradsync.init_dcn_residuals`` layout — ``[n_dev, shard]`` f32
    per bucket) for a resized topology.

    Residuals are positional error mass over the flat gradient: row
    ``dcn*n_inner + ici`` of a bucket holds slice ``ici``'s
    ICI-scattered extent as quantized by that slice.  Across a
    topology change the per-slice attribution is meaningless (the
    slices themselves changed), but the TOTAL outstanding error per
    flat position — the sum over the old outer axis, which is exactly
    what the next EF step would have added back — is portable: it is
    summed out of the old layout, re-split per the new topology's
    shard extents, and spread evenly over the new outer axis (so the
    new outer sum reproduces it).  ``old_shape`` is the old
    ``(n_outer, n_inner)``; the new layout comes from ``mesh`` (default
    current) via ``gradsync.init_dcn_residuals`` — same tree, same
    buckets, new extents.  Returns the re-bucketed state; no error
    mass is dropped (asserted in tests/test_elastic.py).
    """
    import jax.numpy as jnp

    from . import compress, fusion
    from .parallel import gradsync

    old_outer, old_inner = int(old_shape[0]), int(old_shape[1])
    m = mesh if mesh is not None else runtime.current_mesh()
    if axis_names is None:
        axis_names = tuple(m.axis_names)
    outer_ax, inner_ax = compress.ef_axes(axis_names)
    inner_new = int(m.shape[inner_ax])
    outer_new = int(m.shape[outer_ax])
    fresh = gradsync.init_dcn_residuals(
        params_template, axis_names, mesh=m, n_buckets=n_buckets)
    if n_buckets is None:
        n_buckets = runtime.effective_config().gradsync_buckets
    spec = fusion.FusedSpec(params_template,
                            n_buckets=max(1, int(n_buckets)))
    extents = [hi - lo for g in spec.groups for (lo, hi) in g.bounds]
    if len(residuals) != len(fresh):
        raise ValueError(
            f"residual state has {len(residuals)} buckets, the "
            f"template derives {len(fresh)} — re-bucketing needs the "
            f"same tree and n_buckets the state was initialized with")
    out = []
    for old, new, ext in zip(residuals, fresh, extents):
        old = np.asarray(old)
        if old.shape[0] != old_outer * old_inner:
            raise ValueError(
                f"residual rows {old.shape[0]} != old topology "
                f"{old_outer}x{old_inner}")
        # [outer, inner, shard] -> total outstanding error per flat
        # position (old per-row shard padding falls off the extent).
        total = old.reshape(old_outer, old_inner, -1).sum(axis=0)
        flat = total.reshape(-1)[:ext]
        shard_new = int(new.shape[1])
        padded = np.zeros((inner_new * shard_new,), np.float32)
        padded[:ext] = flat
        per_slice = (padded.reshape(inner_new, shard_new)
                     / np.float32(outer_new))
        tiled = np.broadcast_to(
            per_slice, (outer_new, inner_new, shard_new))
        out.append(jnp.asarray(np.ascontiguousarray(
            tiled.reshape(new.shape)).astype(np.float32)))
    return out


def reshard_ps(params: PyTree, *, num_shards: int, old_ps=None):
    """Re-partition a sharded parameter server onto the surviving
    hosts: shut the old instance down (best-effort — some of its shard
    servers may be exactly what died) and re-create it over
    ``num_shards`` fresh shards from the recovered ``params``.  Shard
    extents are a pure function of ``(tree, num_shards)``
    (``parallel/ps.py``), so the re-partition is deterministic."""
    _require_on()
    from . import parameterserver

    if old_ps is not None:
        try:
            old_ps.shutdown()
        except Exception:  # noqa: BLE001 — the dead shard IS the reason
            pass
    return parameterserver.init(params, num_shards=int(num_shards))

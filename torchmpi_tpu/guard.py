"""Guard: end-to-end payload integrity + numeric anomaly detection with
agreed rewind-to-checkpoint (docs/GUARD.md).

Every robustness layer so far handles failures that *announce
themselves* — a raised ``CorruptPayload``, a dead heartbeat, a
``PeerTimeoutError``.  The production failure mode the at-least-once PS
semantics and the DCN transport both invite is **silent**: a
bit-flipped host-staged buffer, a torn PS payload, or a
numerically-diverging step propagates through
``synchronize_gradients`` and poisons every rank with no typed error to
retry.  ``Config.guard`` arms three layers against it:

- **wire** — blake2b digests over every host-staged payload and PS
  exchange, computed at the sender and verified at the receiver
  (:mod:`torchmpi_tpu.faults.integrity`); a mismatch is a typed
  *transient* ``IntegrityError`` the PR 5 policy retries by re-staging
  from the device buffers, feeding ``HealthLedger`` attribution and
  ``tm_guard_*`` telemetry.
- **numeric** — an all-finite + norm-bound tripwire fused into the
  synced-gradient paths (gradsync, the overlap buckets' custom_vjp
  rules, the ZeRO shard legs): ONE fused sum-of-squares reduction per
  bucket (finite iff the sum is finite; the norm bound compares against
  the same scalar), jit-compatible, policy ``skip_step`` (zero the
  update, count it) or ``raise``.
- **full** — both, plus this module's anomaly-rewind driver
  (:func:`run_guarded`): a rolling median/MAD loss-spike detector in
  the step loop; on trip, ranks reach agreement through the PR 10
  membership board (a bounded two-phase verdict + a new ``rewind``
  record) and restore the last fsync-verified ``restart.recover`` step
  *in place* — view, mesh, and every cached CollectivePlan untouched,
  no config-epoch bump — optionally quarantining an implicated peer
  via the ``HealthLedger``.

Off by default and **never imported when off** — the
``analysis``/``obs``/``faults`` import discipline: ``guard="off"``
costs one string compare at plan build / trace time, the planned
dispatch path gains zero branches, and ``import torchmpi_tpu`` never
imports this module (``tests/test_guard.py`` asserts all three).
"""

from __future__ import annotations

import math
import os
import sys
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import runtime
from .utils import checkpoint, restart, telemetry

PyTree = Any

MODES = ("off", "wire", "numeric", "full")

# Agreement sentinel for "this rank did not trip" — far above any real
# step index, so the min over the gang is a trip step iff anyone
# tripped.
_NO_TRIP = 1 << 62


class NumericAnomalyError(RuntimeError):
    """The numeric tripwire (policy ``raise``) or the rewind budget
    tripped: a synced-gradient bucket was non-finite / out of bound, or
    loss spikes kept recurring past ``max_rewinds``."""

    def __init__(self, site: str, *, bucket: int = 0,
                 stat: float = float("nan"), msg: str = ""):
        self.site = site
        self.bucket = int(bucket)
        self.stat = float(stat)
        super().__init__(
            msg or f"numeric anomaly at {site} (bucket {bucket}): "
                   f"sum-of-squares {stat!r} failed the finite/bound "
                   f"check")


# ---------------------------------------------------------------------------
# Module stats (tests + operator spot checks without obs armed)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_stats = {"numeric_trips": 0, "skipped_steps": 0, "rewinds": 0}
_pending: List[NumericAnomalyError] = []


def stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats)


def reset_stats() -> None:
    with _lock:
        for k in _stats:
            _stats[k] = 0
        _pending.clear()


def pending() -> int:
    """Deferred anomalies queued by the ``raise`` policy (see
    :func:`raise_pending`)."""
    with _lock:
        return len(_pending)


def raise_pending() -> None:
    """Raise (and clear) the oldest deferred :class:`NumericAnomalyError`.

    The ``raise`` policy cannot raise from inside the compiled step —
    an exception thrown in a jax debug callback permanently errors the
    runtime's effects token, wedging every later dispatch in the
    process — so the tripped bucket is zeroed in-graph (the poisoned
    update never applies, exactly like ``skip_step``) and the typed
    error is queued here for the next eager boundary.
    ``nn.data_parallel_step`` and :func:`run_guarded` call this after
    every step when the guard is armed; hand-rolled step loops call it
    themselves.  No-op when nothing tripped."""
    with _lock:
        if not _pending:
            return
        e = _pending[0]
        _pending.clear()
    raise e


def _bump(key: str) -> None:
    with _lock:
        _stats[key] += 1


def _record(action: str, site: str, *, peer: str = "") -> None:
    """tm_guard_* through obs when active (the shared sys.modules-gated
    shim — the guard never imports the telemetry it reports to)."""
    telemetry.emit("record_guard", action, site, peer=peer)


# ---------------------------------------------------------------------------
# Layer 2: the numeric tripwire (fused into the synced-grad paths)
# ---------------------------------------------------------------------------


def _on_trip(site: str, bucket: int, policy: str) -> Callable:
    """Runtime half of one fused check: fires per device when the
    bucket's scalar verdict materializes (jax.debug.callback — the
    obs.record_overlap pattern)."""

    def cb(ok, ss) -> None:
        if bool(ok):
            return
        _bump("numeric_trips")
        _record("numeric_tripped", site)
        if policy == "skip_step":
            _bump("skipped_steps")
            _record("skipped_step", site)
            return
        err = NumericAnomalyError(site, bucket=bucket, stat=float(ss))
        with _lock:
            _pending.append(err)

    return cb


def _verdict(ss, norm_bound: float):
    """The fused verdict from one sum-of-squares scalar: finite iff the
    sum is finite (any NaN/Inf element poisons it), and — with a bound
    — ``ss <= bound**2`` rides the SAME scalar, so the whole tripwire
    is one reduction per bucket."""
    ok = jnp.isfinite(ss)
    if norm_bound > 0:
        ok = jnp.logical_and(ok, ss <= jnp.float32(float(norm_bound) ** 2))
    return ok


def check_flat(flat, *, site: str, bucket: int = 0,
               policy: Optional[str] = None,
               norm_bound: Optional[float] = None,
               aux: Optional[List[Tuple[Any, Any]]] = None):
    """Numeric tripwire over one flat (already-synced) bucket — the
    form the overlap custom_vjp rules and the ZeRO shard legs fuse in.
    Trace-time gated by the caller (``Config.guard`` in
    ``numeric``/``full``); jit-compatible.  The tripped bucket comes
    back ZEROED under both policies — the poisoned update must never
    apply — and ``skip_step`` counts it
    (``tm_guard_skipped_step_total``) while ``raise`` queues a typed
    :class:`NumericAnomalyError` for the next eager boundary
    (:func:`raise_pending`).

    ``aux`` is a list of ``(value, fallback)`` array pairs selected
    under the SAME verdict — value when clean, fallback when tripped.
    This is the error-feedback residual contract: a tripped round's
    residuals revert to the pre-step state (as if the round never
    happened) instead of carrying the poisoned error mass into the
    next step's quantized leg.  With ``aux``, returns
    ``(flat, aux_values)``."""
    cfg = runtime.effective_config()
    if policy is None:
        policy = cfg.guard_numeric_policy
    if norm_bound is None:
        norm_bound = cfg.guard_norm_bound
    ss = jnp.sum(jnp.square(flat.astype(jnp.float32)))
    ok = _verdict(ss, norm_bound)
    jax.debug.callback(_on_trip(site, bucket, policy), ok, ss)
    out = jnp.where(ok, flat, jnp.zeros_like(flat))
    if aux is None:
        return out
    return out, [jnp.where(ok, v, fb) for v, fb in aux]


def check_tree(tree, *, site: str, policy: Optional[str] = None,
               norm_bound: Optional[float] = None,
               aux: Optional[List[Tuple[Any, Any]]] = None):
    """Numeric tripwire over a synced gradient pytree (the
    ``synchronize_gradients`` output): per-leaf sums of squares fold
    into ONE scalar verdict — a single fused reduction for the whole
    sync round — and a trip zeroes every leaf together (a half-zeroed
    update would be a worse poison than the anomaly; the ``raise``
    policy defers its typed error to :func:`raise_pending`).  ``aux``
    as in :func:`check_flat` (the EF-residual revert contract); with
    it, returns ``(tree, aux_values)``."""
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree if aux is None else (tree, [v for v, _ in aux])
    cfg = runtime.effective_config()
    if policy is None:
        policy = cfg.guard_numeric_policy
    if norm_bound is None:
        norm_bound = cfg.guard_norm_bound
    ss = jnp.float32(0)
    for leaf in leaves:
        ss = ss + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    ok = _verdict(ss, norm_bound)
    jax.debug.callback(_on_trip(site, 0, policy), ok, ss)
    leaves = [jnp.where(ok, v, jnp.zeros_like(v)) for v in leaves]
    out = jax.tree.unflatten(treedef, leaves)
    if aux is None:
        return out
    return out, [jnp.where(ok, v, fb) for v, fb in aux]


# ---------------------------------------------------------------------------
# Layer 3: loss-spike detection + agreed rewind-to-checkpoint
# ---------------------------------------------------------------------------


class LossSpikeDetector:
    """Rolling median/MAD spike detector over the step-loop loss.

    ``update(loss)`` returns True when the loss is non-finite, or —
    once ``min_history`` observations accumulated — when it exceeds the
    rolling median by ``threshold`` median-absolute-deviations.  The
    MAD has a relative floor (1% of ``max(1, |median|)``) so a
    perfectly flat history cannot make noise trip the detector; a
    tripped value is NOT appended (the spike must not poison the very
    window that detected it).  Defaults come from
    ``Config.guard_spike_window`` / ``guard_spike_threshold``.
    """

    def __init__(self, window: Optional[int] = None,
                 threshold: Optional[float] = None,
                 min_history: int = 5):
        cfg = runtime.effective_config()
        self.window = int(window if window is not None
                          else cfg.guard_spike_window)
        self.threshold = float(threshold if threshold is not None
                               else cfg.guard_spike_threshold)
        self.min_history = int(min_history)
        if self.window < 2 or self.threshold <= 0 or self.min_history < 2:
            raise ValueError(
                f"need window >= 2, threshold > 0, min_history >= 2; got "
                f"{self.window}/{self.threshold}/{self.min_history}")
        self._hist: deque = deque(maxlen=self.window)
        self.last_stat = 0.0  # deviation (in MADs) of the last update

    @staticmethod
    def _median(vals: List[float]) -> float:
        s = sorted(vals)
        n = len(s)
        mid = n // 2
        return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])

    def update(self, loss) -> bool:
        v = float(loss)
        if not math.isfinite(v):
            self.last_stat = float("inf")
            return True
        if len(self._hist) >= self.min_history:
            med = self._median(list(self._hist))
            mad = self._median([abs(x - med) for x in self._hist])
            scale = max(mad, 0.01 * max(1.0, abs(med)))
            self.last_stat = (v - med) / scale
            if self.last_stat > self.threshold:
                return True
        self._hist.append(v)
        return False

    def reset(self) -> None:
        self._hist.clear()
        self.last_stat = 0.0


def _require_on():
    """Consent gate of the driver-layer entry points (the elastic
    pattern: the knob gates the driver, the dispatch path has no branch
    on it)."""
    cfg = runtime.effective_config()
    if cfg.guard == "off":
        raise RuntimeError(
            "torchmpi_tpu.guard requires Config.guard != 'off' (or "
            "TORCHMPI_TPU_GUARD=wire|numeric|full) — the guard layer is "
            "opt-in; see docs/GUARD.md")
    return cfg


def quarantine(peer: str, *, site: str = "rewind") -> bool:
    """Pin ``peer`` dead in the armed fault layer's ``HealthLedger`` —
    the optional attribution half of a rewind: the implicated peer
    stops receiving traffic (PS routing, elastic membership) until a
    successful probe resurrects it.  Returns True iff a ledger was
    actually written; with faults unarmed this is a no-op that reports
    False and emits NOTHING (telemetry must never claim an isolation
    that did not happen)."""
    mod = sys.modules.get("torchmpi_tpu.faults")
    if mod is None or not mod.active():
        return False
    led = mod.ledger()
    for _ in range(led.dead_after):
        led.record(peer, ok=False)
    _record("quarantined", site, peer=peer)
    return True


def agree_rewind(board, tag: str, local_ranks: Sequence[int],
                 members: Sequence[int], trip_step: Optional[int], *,
                 deadline_s: float, poll_s: float) -> Optional[int]:
    """Bounded two-phase rewind verdict over the membership board
    (docs/GUARD.md): phase 1 *proposes* — every rank posts the step it
    tripped at (or the no-trip sentinel) and the bounded min resolves
    to the earliest trip; phase 2 *commits* — every rank acknowledges
    the resolved verdict, so no rank can rewind while another proceeds
    (the same propose-then-commit shape as ``membership.reconcile``,
    minus any view/epoch change).  Returns the agreed trip step, or
    None when nobody tripped (a stale request)."""
    from .faults import membership

    value = _NO_TRIP if trip_step is None else int(trip_step)
    prop = membership.agree_min(board, tag + "p", local_ranks, members,
                                value, deadline_s=deadline_s,
                                poll_s=poll_s)
    # Commit: every rank posts the verdict it resolved; the min of
    # identical values is the value — reaching it proves every member
    # saw (and will act on) the same outcome.
    membership.agree_min(board, tag + "c", local_ranks, members,
                         int(prop), deadline_s=deadline_s, poll_s=poll_s)
    return None if prop >= _NO_TRIP else int(prop)


def run_guarded(init_fn: Callable[[], PyTree],
                step_fn: Callable[[PyTree, int], Tuple[PyTree, Any]],
                *, steps: int, directory: str, save_every: int = 10,
                detector: Optional[LossSpikeDetector] = None,
                max_rewinds: int = 3,
                board_dir: Optional[str] = None,
                members: Optional[Sequence[int]] = None,
                participants: Optional[int] = None,
                agree: Optional[Callable[[int], int]] = None,
                implicate: Optional[str] = None,
                ) -> Tuple[PyTree, Dict[str, Any]]:
    """Run ``steps`` calls of ``step_fn(state, i) -> (state, loss)``
    under the anomaly-rewind guard (docs/GUARD.md).

    Per step the loss feeds the :class:`LossSpikeDetector`; on a trip
    the gang reaches a bounded two-phase verdict over the membership
    board (:func:`agree_rewind` — a ``rewind`` record lands next to
    the reconcile history) and restores the last fsync-verified
    checkpoint via :func:`restart.recover` **in place**: the view, the
    mesh, and every cached CollectivePlan are untouched and the config
    epoch does not move (asserted in tests/test_guard.py) — a rewind
    is a state restore, not a re-plan.  With ``Config.ckpt_redundancy``
    on (docs/CHECKPOINT.md) the rewind target is digest-verified and
    buddy-repairable — a rewind whose checkpoint rotted walks back to
    the next verifiable step instead of restoring garbage — and the
    step each rewind settles on is pinned against ``ckpt_keep``
    retention so a chaos soak cannot prune its own rewind target.  ``implicate`` optionally
    quarantines a peer in the ``HealthLedger`` at each rewind.  Every
    rank of a multi-process gang must call this collectively (the
    ``restart.recover`` contract); the single-process sim degrades to
    a trivially-agreeing board.  A trip that keeps recurring past
    ``max_rewinds`` raises :class:`NumericAnomalyError` — rewinding
    forever over a deterministically-poisoned input would be the
    silent failure this module exists to end.

    Returns ``(state, info)`` with ``info`` carrying ``rewinds`` /
    ``trip_steps`` / ``steps_run`` / ``recovered_step``.
    """
    cfg = _require_on()
    if steps < 0:
        raise ValueError(f"steps must be >= 0, got {steps}")
    from .faults import membership

    det = detector if detector is not None else LossSpikeDetector()
    board = membership.Board(board_dir
                             or os.path.join(directory, "membership"))
    multi = jax.process_count() > 1
    local: Tuple[int, ...] = (jax.process_index(),) if multi else (0,)
    if members is None:
        members = (tuple(range(jax.process_count())) if multi else (0,))
    members = tuple(sorted(int(m) for m in members))
    if participants is None:
        participants = len(members) if multi else 1
    deadline_s = float(cfg.elastic_deadline_s)
    poll_s = float(cfg.elastic_poll_s)

    # A previous life's in-flight protocol state must not poison this
    # one (the ElasticGang construction-time discipline): drop our own
    # agreement values and any stale rewind request; continue the round
    # numbering past recorded rewinds so a restarted driver neither
    # resolves a dead life's values nor overwrites its post-mortem
    # records (every rank reads the same records, so the numbering
    # stays lockstep).
    for r in local:
        board.clear_values(r)
        board.clear_rewind_request(r)
    rounds = max([int(d.get("round", 0))
                  for d in board.rewind_records()] or [0])

    template = init_fn()
    state, i = restart.recover(init_fn, directory, template,
                               participants=participants, agree=agree)
    recovered_step = i
    rewinds = 0
    steps_run = 0
    trip_steps: List[int] = []

    def commit_rewind(agreed: int):
        nonlocal rewinds, recovered_step, state, i
        rewinds += 1
        trip_steps.append(agreed)
        _bump("rewinds")
        _record("rewind", "loss_spike")
        quarantined = bool(implicate) and quarantine(implicate)
        board.post_rewind_record(rounds, {
            "step": int(agreed), "stat": float(det.last_stat),
            "peer": implicate or "",
            "quarantined": quarantined,
            "members": list(members)})
        if rewinds > max_rewinds:
            raise NumericAnomalyError(
                "loss_spike", stat=det.last_stat,
                msg=f"loss spike at step {agreed} kept recurring "
                    f"past the rewind budget ({max_rewinds})")
        state, i = restart.recover(
            init_fn, directory, template,
            participants=participants, agree=agree)
        recovered_step = i
        # Fresh eyes after the restore: the rolled-back segment's
        # losses would otherwise sit in the window while the replay
        # re-appends the same steps — duplicated history collapses the
        # MAD and makes the post-rewind detector more trigger-happy
        # than the configured threshold (code review).  The cost is
        # min_history steps of detection grace after each rewind.
        det.reset()

    while True:
        while i < steps:
            # Step boundary for obs_tool attribute (ring-only; the
            # telemetry shim makes it a no-op when obs is off).
            telemetry.emit("record_step", "run_guarded", i)
            state, loss = step_fn(state, i)
            steps_run += 1
            raise_pending()  # the tripwire's raise-policy boundary
            tripped = det.update(loss)
            if multi and tripped:
                board.request_rewind(local[0], step=i,
                                     stat=det.last_stat)
            pending = tripped or (multi
                                  and bool(board.rewind_requests()))
            if pending:
                rounds += 1
                agreed = agree_rewind(
                    board, f"rw{rounds}", local, members,
                    i if tripped else None,
                    deadline_s=deadline_s, poll_s=poll_s)
                for r in local:
                    board.clear_rewind_request(r)
                if agreed is not None:
                    commit_rewind(agreed)
                    continue
            i += 1
            if i % save_every == 0 or i == steps:
                checkpoint.save(directory, state, step=i)
        if not multi:
            break
        # Closing agreement: a peer whose detector tripped at its FINAL
        # step is blocked in a round this rank's per-step poll may have
        # missed (the request landed after our last listdir) — every
        # rank joins one more round at exit, so no rank can return
        # while another waits on it.  The round counter stays lockstep:
        # the tripped peer's in-loop round and our closing round are
        # the same tag.  A rewind verdict re-enters the step loop on
        # every rank; a no-trip verdict ends the run everywhere.
        rounds += 1
        agreed = agree_rewind(board, f"rw{rounds}", local, members, None,
                              deadline_s=deadline_s, poll_s=poll_s)
        for r in local:
            board.clear_rewind_request(r)
        if agreed is None:
            break
        commit_rewind(agreed)
    return state, {"rewinds": rewinds, "trip_steps": trip_steps,
                   "steps_run": steps_run,
                   "recovered_step": recovered_step}

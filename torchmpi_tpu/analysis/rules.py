"""The rule registry: what the analyzer checks over the event stream.

Each rule is a function ``fn(ctx) -> list[Finding]`` over a
:class:`RuleContext` (the collective-event stream from
:mod:`torchmpi_tpu.analysis.events`, the trace-time fusion/ZeRO layout
records, and the active config).  Rules register under a short id; the
default run executes all of them, ``check(..., rules=("D1", "P1"))``
selects a subset.

Shipped rules
=============

Deadlock / correctness (error severity):

- **D1** — collective under a ``cond``/``switch`` branch whose predicate
  derives from ``axis_index`` (device rank).  Different devices of the
  same SPMD program can take different branches, so a collective inside
  one branch is only entered by a subset of ranks: the classic SPMD
  divergence deadlock.
- **D2** — collective over an axis name not bound by any enclosing
  mesh/``shard_map``/``axis_env``.  Today this surfaces as a late,
  cryptic trace/XLA error; the rule reports it with provenance (the
  checker also converts jax's trace-time "unbound axis name" failure
  into this finding).
- **C1** — fused-collective / ZeRO layout invariants, re-verified on the
  actual traced program: the ``FusedSpec`` a fused launch ran with must
  match the tree it was applied to, a requested ``gradsync_barrier``
  chain must span ALL dtype-group buckets, and a ZeRO reduce-scatter's
  shard layout (``n_shards``, per-group padding) must agree with the
  axes it actually spans.
- **C2** — DCN compression / layout consistency
  (``config.dcn_compress`` — docs/HIERARCHICAL.md): a codec requested
  for a reduction that cannot ride the quantized sum path (max/min,
  integer payloads) is an error (the leg silently ran uncompressed); an
  error-feedback residual state whose structure does not match the
  gradient bucket layout is an error (the runtime raise carries no
  provenance; this finding does); a quantized leg on a payload below
  ``dcn_compress_min_bytes`` is informational (the floor did its job —
  but a config expecting compression savings should know).

Hazards / performance (warning or info severity):

- **D3** — mixed-ordering hazard: two branches of the same
  ``cond``/``switch`` issue the same collectives over the same axes in
  different orders.  If the branch selection ever diverges across ranks
  the collectives pair up crosswise and deadlock; even rank-uniform
  programs are one refactor away.
- **P1** — >= ``P1_MIN_COUNT`` small same-dtype, same-axes elementwise
  collectives in one jaxpr region: the per-leaf launch pattern the
  fused pytree path (``config.fuse_max_bytes``) exists to coalesce.
- **P2** — collective whose payload falls below the selector's
  cutover/plan bucket floor (``config.custom_min_bytes``): a transfer
  too small to ever route to a measured custom backend — the "tiny
  collective nobody measured" case.  Payloads under
  ``P2_MIN_NBYTES`` (scalar loss reductions etc.) are exempt.

Decode / serving slice safety (:mod:`torchmpi_tpu.analysis.slices`):

- **S1** — ``dynamic_update_slice``/``dynamic_slice`` (and the
  ``mode=CLIP`` scatter ``vmap`` lowers per-row updates to) whose start
  index is data-dependent and not provably clamped to leave room for
  the update width — the PR 17 slot-cache silent-corruption class.
  Error when the write target is a carried cache buffer, info
  otherwise.
- **S2** — per-row slot-cache writes whose ``pos_offset`` bypasses the
  ``clamp_slot_positions`` helper (``models/generate.py`` /
  ``tp_generate``) — the clamp may exist inline, but the chokepoint
  discipline is what keeps the next width change safe.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .events import CollectiveEvent
from .findings import ERROR, INFO, WARNING, Finding

# P1: how many coalescable small collectives constitute a hot-path
# fusion bypass worth flagging.
P1_MIN_COUNT = 4
# P2: payloads at or under this are intentionally tiny (scalar losses,
# flags) and exempt from the "nobody measured this size" report.
P2_MIN_NBYTES = 256

# Elementwise-fusable primitives (what allreduce/reduce/broadcast lower
# to): the ops fusion.ELEMENTWISE_OPS would have coalesced.
_P1_PRIMITIVES = ("psum", "pmin", "pmax")


@dataclasses.dataclass
class RuleContext:
    """Everything one rule invocation may consult."""

    events: Sequence[CollectiveEvent]
    records: Sequence[dict]          # fusion/ZeRO trace-time records
    config: object                   # the effective Config
    label: str = ""                  # caller-supplied name of the fn
    # Dynamic-slice event stream (analysis/slices.py) for the S rules;
    # default () keeps record-only constructions (C2's partial-trace
    # path) working unchanged.
    slice_events: Sequence[object] = ()


@dataclasses.dataclass
class Rule:
    id: str
    severity: str
    doc: str
    fn: Callable[[RuleContext], List[Finding]]


RULES: Dict[str, Rule] = {}


def register_rule(id: str, severity: str, doc: str):
    def deco(fn):
        RULES[id] = Rule(id=id, severity=severity, doc=doc, fn=fn)
        return fn
    return deco


def resolve_rules(rules: Optional[Sequence[str]] = None) -> List[Rule]:
    if rules is None:
        return list(RULES.values())
    out = []
    for r in rules:
        if r not in RULES:
            raise ValueError(
                f"unknown analysis rule {r!r} (known: {sorted(RULES)})")
        out.append(RULES[r])
    return out


def run_rules(ctx: RuleContext,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for rule in resolve_rules(rules):
        findings.extend(rule.fn(ctx))
    return findings


# ---------------------------------------------------------------------------
# D1: collective under a rank-derived branch (SPMD divergence deadlock)
# ---------------------------------------------------------------------------


@register_rule("D1", ERROR,
               "collective under a cond/switch branch whose predicate "
               "derives from axis_index (rank): SPMD divergence deadlock")
def _rule_d1(ctx: RuleContext) -> List[Finding]:
    out = []
    for ev in ctx.events:
        if not ev.under_divergent_cond:
            continue
        frame = next(f for f in ev.cond_stack if f.pred_tainted)
        out.append(Finding(
            rule="D1", severity=ERROR,
            message=(f"{ev.primitive} inside branch {frame.branch} of a "
                     f"cond whose predicate derives from axis_index: "
                     f"ranks taking the other branch never enter this "
                     f"collective (deadlock on hardware)"),
            path=ev.path, source=ev.source or frame.source,
            op=ev.primitive, axes=ev.axes, nbytes=ev.nbytes))
    return out


# ---------------------------------------------------------------------------
# D2: collective over an unbound axis name
# ---------------------------------------------------------------------------


@register_rule("D2", ERROR,
               "collective over an axis name not bound by any enclosing "
               "mesh/shard_map/axis_env")
def _rule_d2(ctx: RuleContext) -> List[Finding]:
    out = []
    for ev in ctx.events:
        missing = ev.unbound_axes
        if not missing:
            continue
        out.append(Finding(
            rule="D2", severity=ERROR,
            message=(f"{ev.primitive} names axis "
                     f"{'/'.join(missing)} which no enclosing mesh or "
                     f"shard_map binds (bound here: "
                     f"{sorted(ev.bound_axes) or 'none'})"),
            path=ev.path, source=ev.source,
            op=ev.primitive, axes=ev.axes, nbytes=ev.nbytes))
    return out


def unbound_axis_finding(exc: BaseException, label: str = "") -> Finding:
    """Convert jax's trace-time unbound-axis failure into the D2 finding
    (the checker calls this when ``make_jaxpr`` itself raises)."""
    return Finding(
        rule="D2", severity=ERROR,
        message=(f"tracing failed with {type(exc).__name__}: {exc} — a "
                 f"collective names an axis no enclosing mesh/shard_map/"
                 f"axis_env binds"),
        path=label)


# ---------------------------------------------------------------------------
# D3: mixed collective ordering across branches of one cond
# ---------------------------------------------------------------------------


@register_rule("D3", WARNING,
               "same-axis collectives issued in different orders along "
               "different branches of the same cond/switch")
def _rule_d3(ctx: RuleContext) -> List[Finding]:
    # site id -> branch idx -> ordered [(primitive, axes)]
    sites: Dict[int, Dict[int, List[Tuple[str, Tuple[str, ...]]]]] = {}
    meta: Dict[int, Tuple[str, str]] = {}  # site -> (source, path)
    for ev in ctx.events:
        for frame in ev.cond_stack:
            sig = (ev.primitive, ev.axes)
            sites.setdefault(frame.site, {}).setdefault(
                frame.branch, []).append(sig)
            meta.setdefault(frame.site, (frame.source, ev.path))
    out = []
    for site, branches in sites.items():
        # ALL branch pairs, not just adjacent ones: an intervening
        # branch with < 2 collectives must not mask a b0-vs-b2
        # reordering.  Branch counts are tiny; O(n^2) is free.
        seqs = [(b, s) for b, s in sorted(branches.items())
                if len(s) >= 2]
        done = False
        for i, (bi, si) in enumerate(seqs):
            for bj, sj in seqs[i + 1:]:
                if si != sj and sorted(si) == sorted(sj):
                    src, path = meta[site]
                    ops = ", ".join(f"{p} over {'x'.join(a)}"
                                    for p, a in si)
                    out.append(Finding(
                        rule="D3", severity=WARNING,
                        message=(f"branches {bi} and {bj} of this cond "
                                 f"issue the same collectives ({ops}) "
                                 f"in different orders: if branch "
                                 f"selection ever diverges across "
                                 f"ranks the collectives pair up "
                                 f"crosswise and deadlock"),
                        path=path, source=src))
                    done = True  # one finding per cond site
                    break
            if done:
                break
    return out


# ---------------------------------------------------------------------------
# P1: per-leaf launches that bypassed the fused path
# ---------------------------------------------------------------------------


@register_rule("P1", WARNING,
               "many small same-dtype elementwise collectives that the "
               "fused pytree path would coalesce")
def _rule_p1(ctx: RuleContext) -> List[Finding]:
    fuse_max = int(getattr(ctx.config, "fuse_max_bytes", 0) or 0)
    if fuse_max <= 0:
        return []  # fusion disabled on purpose: nothing bypassed it
    groups: Dict[Tuple, List[CollectiveEvent]] = {}
    for ev in ctx.events:
        if ev.primitive not in _P1_PRIMITIVES:
            continue
        if not (0 < ev.nbytes < fuse_max):
            continue
        groups.setdefault(
            (ev.region, ev.primitive, ev.axes, ev.dtype), []).append(ev)
    out = []
    for (region, prim, axes, dtype), evs in groups.items():
        if len(evs) < P1_MIN_COUNT:
            continue
        total = sum(e.nbytes for e in evs)
        out.append(Finding(
            rule="P1", severity=WARNING,
            message=(f"{len(evs)} separate {prim} launches of small "
                     f"{dtype} buffers ({total} bytes total) in one "
                     f"region: the fused pytree path "
                     f"(config.fuse_max_bytes={fuse_max}) would coalesce "
                     f"these into "
                     f"{max(1, -(-total // fuse_max))} launch(es)"),
            path=evs[0].path, source=evs[0].source,
            op=prim, axes=axes, nbytes=total))
    return out


# ---------------------------------------------------------------------------
# P2: collective below the selector cutover / plan bucket floor
# ---------------------------------------------------------------------------


@register_rule("P2", INFO,
               "collective payload below the selector's cutover/plan "
               "bucket floor: too small to ever route to a measured "
               "custom backend")
def _rule_p2(ctx: RuleContext) -> List[Finding]:
    floor = int(getattr(ctx.config, "custom_min_bytes", 0) or 0)
    if floor <= 0:
        return []
    out = []
    for ev in ctx.events:
        if not (P2_MIN_NBYTES <= ev.nbytes < floor):
            continue
        out.append(Finding(
            rule="P2", severity=INFO,
            message=(f"{ev.primitive} payload of {ev.nbytes} bytes is "
                     f"below the custom-backend cutover "
                     f"(custom_min_bytes={floor}): it always takes the "
                     f"stock path and no tuning plan will ever measure "
                     f"this size — consider fusing it with neighbors"),
            path=ev.path, source=ev.source,
            op=ev.primitive, axes=ev.axes, nbytes=ev.nbytes))
    return out


# ---------------------------------------------------------------------------
# C1: fused / ZeRO shard-layout invariants (from trace-time records)
# ---------------------------------------------------------------------------


@register_rule("C1", ERROR,
               "fused-collective / ZeRO layout invariants: spec matches "
               "tree, barrier chain spans all dtype-group buckets, shard "
               "layout agrees with the axes spanned")
def _rule_c1(ctx: RuleContext) -> List[Finding]:
    out = []
    for rec in ctx.records:
        kind = rec.get("kind")
        src = rec.get("source", "")
        if kind == "fuse_tree":
            if rec.get("spec_leaves") != rec.get("tree_leaves") or \
                    rec.get("spec_dtypes") != rec.get("tree_dtypes") or \
                    rec.get("spec_sizes") != rec.get("tree_sizes"):
                out.append(Finding(
                    rule="C1", severity=ERROR,
                    message=(f"fused {rec.get('op')} ran with a FusedSpec "
                             f"built for a different tree "
                             f"({rec.get('spec_leaves')} leaves/"
                             f"{rec.get('spec_sizes')} sizes vs "
                             f"{rec.get('tree_leaves')}/"
                             f"{rec.get('tree_sizes')} actual): leaves "
                             f"unpack from the wrong extents"),
                    source=src, op=str(rec.get("op", "")),
                    axes=tuple(rec.get("axes", ()))))
            n_launches = int(rec.get("n_launches", 1))
            if rec.get("barrier") and n_launches > 1 and \
                    int(rec.get("barrier_links", 0)) != n_launches - 1:
                out.append(Finding(
                    rule="C1", severity=ERROR,
                    message=(f"gradsync_barrier chain covers "
                             f"{rec.get('barrier_links')} of the "
                             f"{n_launches - 1} bucket transitions: "
                             f"unchained buckets re-merge in XLA's "
                             f"all-reduce combiner"),
                    source=src, op=str(rec.get("op", "")),
                    axes=tuple(rec.get("axes", ()))))
        elif kind == "zero_reduce_scatter":
            n_shards = int(rec.get("n_shards", 1))
            axis_size = int(rec.get("axis_size", n_shards))
            if n_shards != axis_size:
                out.append(Finding(
                    rule="C1", severity=ERROR,
                    message=(f"ZeRO shard layout was built for "
                             f"{n_shards} shards but the reduce_scatter "
                             f"axes span {axis_size} devices: every "
                             f"device updates the wrong parameter "
                             f"extent"),
                    source=src, axes=tuple(rec.get("axes", ()))))
            for dtype, padded, shard in rec.get("groups", ()):
                if n_shards and padded % n_shards != 0:
                    out.append(Finding(
                        rule="C1", severity=ERROR,
                        message=(f"ZeRO {dtype} group padded length "
                                 f"{padded} is not divisible by "
                                 f"n_shards={n_shards}: group-major "
                                 f"shard extents misalign"),
                        source=src, axes=tuple(rec.get("axes", ()))))
    return out


# ---------------------------------------------------------------------------
# C2: DCN compression / layout consistency (from trace-time records —
# compress.note_leg / compress.residual_note / hierarchical._dcn_codec)
# ---------------------------------------------------------------------------


@register_rule("C2", ERROR,
               "DCN compression consistency: codec vs reduce op, "
               "error-feedback residual structure vs the gradient bucket "
               "layout, quantized legs below the size floor")
def _rule_c2(ctx: RuleContext) -> List[Finding]:
    out = []
    for rec in ctx.records:
        kind = rec.get("kind")
        src = rec.get("source", "")
        if kind == "dcn_compress":
            op = str(rec.get("op", ""))
            codec = str(rec.get("codec", ""))
            if rec.get("incompatible"):
                out.append(Finding(
                    rule="C2", severity=ERROR,
                    message=(f"dcn_compress={codec!r} requested but this "
                             f"two-level {op} cannot quantize its DCN leg "
                             f"(non-sum reduction or non-float payload): "
                             f"the leg silently ran uncompressed — drop "
                             f"the codec for this op or route it "
                             f"separately"),
                    source=src, op=op, axes=tuple(rec.get("axes", ())),
                    nbytes=int(rec.get("nbytes", 0))))
            elif (int(rec.get("nbytes", 0))
                    < int(rec.get("min_bytes", 0))
                    and int(rec.get("wire_nbytes", 0))
                    == int(rec.get("nbytes", 0))):
                out.append(Finding(
                    rule="C2", severity=INFO,
                    message=(f"dcn_compress={codec!r} is on but this "
                             f"{op}'s DCN shard ({rec.get('nbytes')} "
                             f"bytes) is below dcn_compress_min_bytes="
                             f"{rec.get('min_bytes')}: it crossed DCN "
                             f"uncompressed (the floor working as "
                             f"designed — raise it deliberately or fuse "
                             f"the payload if savings were expected)"),
                    source=src, op=op, axes=tuple(rec.get("axes", ())),
                    nbytes=int(rec.get("nbytes", 0))))
        elif kind == "dcn_residual" and not rec.get("ok", True):
            out.append(Finding(
                rule="C2", severity=ERROR,
                message=(f"error-feedback residual state does not match "
                         f"the gradient bucket layout: {rec.get('got')} "
                         f"residual buffer(s) threaded for "
                         f"{rec.get('expected')} bucket(s) — build the "
                         f"state with init_dcn_residuals(...) from the "
                         f"SAME template/n_buckets/max_bytes as the sync"),
                source=src, axes=tuple(rec.get("axes", ()))))
    return out


@register_rule("S1", ERROR,
               "dynamic_update_slice/dynamic_slice start index not "
               "provably clamped to leave room for the update width")
def _rule_s1(ctx: RuleContext) -> List[Finding]:
    """The PR 17 slot-cache corruption class, statically: an
    out-of-range ``dynamic_update_slice`` start CLAMPS instead of
    failing (so does the ``mode=CLIP`` scatter ``vmap`` lowers the
    per-row form to), silently overwriting the last in-range rows.  A
    data-dependent start feeding a cache write must be provably bounded
    — ``jnp.clip``/``lax.clamp`` against ``size - width`` — before the
    slice.  Error when the write target is a carried/input cache
    buffer; info for reads and scratch intermediates."""
    out: List[Finding] = []
    for ev in ctx.slice_events:
        if ev.safe:
            continue
        hot = ev.write and ev.on_buffer
        kind = "write" if ev.write else "read"
        target = ("carried cache buffer" if ev.on_buffer
                  else "intermediate value")
        out.append(Finding(
            rule="S1", severity=ERROR if hot else INFO,
            message=(f"{ev.op} {kind} into a {target} with an "
                     f"unproven start index ({ev.detail}): an "
                     f"out-of-range start CLAMPS silently — corrupt "
                     f"last rows, no error.  Clamp the index to "
                     f"[0, size - width] (models/generate.py:"
                     f"clamp_slot_positions) before the slice"),
            path=ev.path, source=ev.source, op=ev.op))
    return out


@register_rule("S2", WARNING,
               "slot-indexed cache write whose positions bypass the "
               "clamp helpers in models/generate.py/tp_generate.py")
def _rule_s2(ctx: RuleContext) -> List[Finding]:
    """Per-row (vmapped) slot-cache writes must derive their
    ``pos_offset`` through :func:`models.generate.clamp_slot_positions`
    — the helper both clamps AND leaves a ``slot_clamp`` trace record,
    so the discipline is checkable here.  An inline ``jnp.clip`` may
    satisfy S1 today, but the next edit to the width or the buffer
    shape has no single chokepoint to keep it honest."""
    batched = [ev for ev in ctx.slice_events
               if ev.write and ev.batched and ev.data_dependent]
    if not batched:
        return []
    if any(r.get("kind") == "slot_clamp" for r in ctx.records):
        return []
    ev = batched[0]
    return [Finding(
        rule="S2", severity=WARNING,
        message=(f"{len(batched)} per-row slot-cache write(s) trace "
                 f"without a clamp-helper record: route the positions "
                 f"through models/generate.py:clamp_slot_positions "
                 f"(or tp_generate's re-export) instead of deriving "
                 f"pos_offset ad hoc"),
        path=ev.path, source=ev.source, op=ev.op)]


def rule_catalog() -> List[Tuple[str, str, str]]:
    """(id, severity, doc) for every registered rule — docs/CLI help."""
    return [(r.id, r.severity, r.doc) for r in RULES.values()]

"""Opt-in runtime hook: run the analyzer once per jit-cache entry.

``Config.analysis`` (env ``TORCHMPI_TPU_ANALYSIS``) turns this on:

- ``"warn"``  — findings are emitted as Python warnings; execution
  continues.
- ``"error"`` — error-severity findings raise :class:`AnalysisError`
  before the offending program ever compiles.

The hook sits at the two places the library compiles user-facing
programs — ``collectives._eager_collective`` (one check per executable
cache entry) and the step builders in ``parallel/gradsync`` /
``recipes`` (one check per argument-shape signature).  The check is
trace-time only and runs exactly once per cache entry: with
``Config.analysis="off"`` (the default) none of this module is even
imported, so the steady-state step cost is identical to a build without
the analyzer.

When ``TORCHMPI_TPU_ANALYSIS_OUT`` names a file, every finding the
process produced is written there as JSON at exit (clean runs write an
empty list) — the transport ``scripts/lint_collectives.py`` uses to
lint example entry points without parsing stdout.
"""

from __future__ import annotations

import atexit
import json
import os
import warnings
from typing import Callable, List

from .checker import check
from .findings import Finding, format_findings, has_errors

MODES = ("off", "warn", "error")

ANALYSIS_OUT_ENV = "TORCHMPI_TPU_ANALYSIS_OUT"


class AnalysisError(RuntimeError):
    """Raised under ``Config.analysis="error"`` when the checker finds
    an error-severity problem in a program about to compile."""

    def __init__(self, label: str, findings: List[Finding]):
        self.findings = findings
        super().__init__(
            f"collective-consistency analysis of {label!r}:\n"
            f"{format_findings(findings)}")


# Every finding any runtime check produced, in order (for the atexit
# JSON report and for tests).
_captured: List[Finding] = []
_atexit_armed = False


def captured_findings() -> List[Finding]:
    return list(_captured)


def reset_captured() -> None:
    _captured.clear()


def _write_report() -> None:
    path = os.environ.get(ANALYSIS_OUT_ENV)
    if not path:
        return
    try:
        with open(path, "w") as f:
            json.dump([fi.to_json() for fi in _captured], f, indent=1)
    except OSError:
        pass  # best-effort: a report failure must not mask the run


def arm_runtime_capture() -> None:
    """Idempotently register the atexit JSON report (called by
    ``runtime.init`` when ``Config.analysis`` is on, so the report file
    exists — possibly empty — for every analyzed process)."""
    global _atexit_armed
    if not _atexit_armed:
        _atexit_armed = True
        atexit.register(_write_report)
        # An armed process with no checks yet should still produce the
        # (empty) report if it dies early.
        _write_report()


def report(label: str, findings: List[Finding], mode: str) -> None:
    """Deliver one check's findings per the configured mode.

    Info-severity findings are captured (for the JSON report and
    ``captured_findings``) but never surfaced as Python warnings — a
    tiny-payload observation must not nag every training run that
    opted into the checker."""
    _captured.extend(findings)
    if not findings:
        return
    if mode == "error" and has_errors(findings):
        raise AnalysisError(label, findings)
    loud = [f for f in findings if f.severity != "info"]
    if loud:
        warnings.warn(
            f"torchmpi_tpu.analysis[{label}]:\n{format_findings(loud)}",
            stacklevel=3)


def check_once(label: str, fn, *args, mode: str,
               axis_env=None) -> List[Finding]:
    """Run the checker on one about-to-compile program and report per
    ``mode``.  The caller is responsible for the once-per-cache-entry
    discipline (it owns the cache)."""
    findings = check(fn, *args, axis_env=axis_env, label=label)
    report(label, findings, mode)
    return findings


def wrap_step(delegate: Callable, traceable: Callable, *, label: str,
              mode: str) -> Callable:
    """Wrap a jitted step so each new argument-shape signature is
    analyzed (trace-only) before the delegate runs it.

    ``traceable`` is the pre-jit function (the jitted wrapper itself
    cannot be retraced by ``make_jaxpr``); the signature cache mirrors
    jit's own, so the check runs exactly once per compiled entry.
    """
    import jax

    seen = set()

    def signature(args):
        return tuple(
            (getattr(l, "shape", None), str(getattr(l, "dtype", "")))
            for a in args for l in jax.tree.leaves(a))

    def checked(*args):
        sig = signature(args)
        if sig not in seen:
            # Mark seen only AFTER a passing check: under mode="error"
            # a retried call with the same shapes must re-check (and
            # re-raise), never silently run the flagged program.
            check_once(label, traceable, *args, mode=mode)
            seen.add(sig)
        return delegate(*args)

    checked.jitted = getattr(delegate, "jitted", delegate)
    return checked

"""Jaxpr walker: turn a traced step function into a stream of
collective events.

The walker descends recursively through every higher-order primitive
that carries sub-jaxprs — ``pjit``, ``shard_map``, ``scan``, ``while``,
``cond``/``switch`` branches, ``custom_vjp``/``custom_jvp`` calls,
``remat`` — and records one :class:`CollectiveEvent` per collective
primitive it meets (``psum``/``pmin``/``pmax``, ``all_gather``,
``reduce_scatter``, ``ppermute``, ``all_to_all`` — everything the
``collectives.py`` wrappers lower to).

Alongside the events it maintains the two pieces of context the rules
need and a grep of the final HLO could never recover:

- **bound axes**: which mesh axis names are live at each event
  (``shard_map`` meshes, ``pmap`` axes, plus the ``axis_env`` the
  caller traced under) — rule D2's input.
- **rank taint**: a forward dataflow pass marking every intermediate
  value derived from ``axis_index`` (device rank).  A ``cond`` whose
  predicate is rank-tainted can take different branches on different
  devices of the same SPMD program — rule D1's input.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

# Collective primitives and where each keeps its axis names.  psum also
# covers pmean (psum + div) and the masked broadcast/reduce forms the
# in-axis wrappers lower to.
_COLLECTIVE_AXIS_PARAM = {
    "psum": "axes",
    "pmin": "axes",
    "pmax": "axes",
    "all_gather": "axis_name",
    "all_gather_invariant": "axis_name",
    "reduce_scatter": "axis_name",
    "psum_scatter": "axis_name",
    "ppermute": "axis_name",
    "all_to_all": "axis_name",
    "pgather": "axis_name",
}

# Primitives whose outputs are rank-derived by definition.
_RANK_SOURCES = ("axis_index",)


@dataclasses.dataclass
class CondFrame:
    """One enclosing ``cond``/``switch`` branch around an event."""

    site: int          # per-walk unique id of the cond equation
    branch: int        # which branch the event sits in
    n_branches: int
    pred_tainted: bool  # predicate is derived from axis_index/rank
    source: str = ""   # user frame of the cond itself


@dataclasses.dataclass
class CollectiveEvent:
    """One collective issued somewhere inside the traced step."""

    index: int                     # issue order over the whole walk
    primitive: str                 # jaxpr primitive name
    axes: Tuple[str, ...]          # named axes the collective spans
    nbytes: int                    # payload bytes (sum of array operands)
    dtype: str                     # first array operand's dtype name
    path: str                      # jaxpr traversal path
    source: str                    # user frame (file:line (fn)) or ""
    bound_axes: FrozenSet[str]     # axis names live at this point
    cond_stack: Tuple[CondFrame, ...] = ()
    region: int = 0                # id of the immediately containing jaxpr

    @property
    def unbound_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in self.axes if a not in self.bound_axes)

    @property
    def under_divergent_cond(self) -> bool:
        return any(f.pred_tainted for f in self.cond_stack)


def _user_source(source_info) -> str:
    """Best-effort ``file.py:line (fn)`` from an equation's source_info."""
    try:
        from jax._src import source_info_util

        fr = source_info_util.user_frame(source_info)
        if fr is None:
            return ""
        name = getattr(fr, "function_name", "") or ""
        return f"{fr.file_name}:{fr.start_line}" + (f" ({name})" if name
                                                    else "")
    except Exception:  # noqa: BLE001 — provenance is best-effort
        return ""


def _axis_names(params: dict, key: str) -> Tuple[str, ...]:
    v = params.get(key, ())
    if isinstance(v, str):
        return (v,)
    try:
        return tuple(a for a in v if isinstance(a, str))
    except TypeError:
        return ()


def _aval_nbytes(avals: Sequence) -> Tuple[int, str]:
    total, dtype = 0, ""
    for a in avals:
        shape = getattr(a, "shape", None)
        dt = getattr(a, "dtype", None)
        if shape is None or dt is None:
            continue
        total += int(np.prod(shape)) * np.dtype(dt).itemsize
        if not dtype:
            dtype = np.dtype(dt).name
    return total, dtype


def _subjaxprs(value) -> List:
    """Open ``Jaxpr``s reachable from one eqn param value."""
    out = []
    stack = [value]
    while stack:
        v = stack.pop()
        if hasattr(v, "eqns") and hasattr(v, "invars"):
            out.append(v)
        elif hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
            out.append(v.jaxpr)
        elif isinstance(v, (tuple, list)):
            stack.extend(v)
    return out


def _mesh_axis_names(mesh) -> Tuple[str, ...]:
    try:
        return tuple(str(a) for a in mesh.axis_names)
    except Exception:  # noqa: BLE001 — AbstractMesh variants
        try:
            return tuple(str(a) for a in dict(mesh.shape))
        except Exception:  # noqa: BLE001
            return ()


class _Walker:
    def __init__(self, bound_axes: FrozenSet[str]):
        self.events: List[CollectiveEvent] = []
        self.counter = 0
        self.cond_sites = 0
        self.region_ids: Dict[int, int] = {}
        self.initial_bound = bound_axes

    def _region(self, jaxpr) -> int:
        return self.region_ids.setdefault(id(jaxpr), len(self.region_ids))

    # -- taint plumbing ----------------------------------------------------

    @staticmethod
    def _tainted(v, taint: set) -> bool:
        # Literals carry no var identity and are never rank-derived.
        return not hasattr(v, "val") and v in taint

    def _any_tainted(self, vs, taint: set) -> bool:
        return any(self._tainted(v, taint) for v in vs)

    # -- the walk ----------------------------------------------------------

    def walk(self, jaxpr, *, bound: FrozenSet[str], taint: set,
             path: str, cond_stack: Tuple[CondFrame, ...]) -> set:
        """Walk one (open) jaxpr; returns the set of tainted outvars."""
        region = self._region(jaxpr)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            in_tainted = self._any_tainted(eqn.invars, taint)

            if name in _RANK_SOURCES:
                taint.update(eqn.outvars)
                continue

            if name in _COLLECTIVE_AXIS_PARAM:
                axes = _axis_names(eqn.params,
                                   _COLLECTIVE_AXIS_PARAM[name])
                nbytes, dtype = _aval_nbytes(
                    [v.aval for v in eqn.invars if hasattr(v, "aval")])
                self.events.append(CollectiveEvent(
                    index=self.counter, primitive=name, axes=axes,
                    nbytes=nbytes, dtype=dtype, path=path,
                    source=_user_source(eqn.source_info),
                    bound_axes=bound, cond_stack=cond_stack,
                    region=region))
                self.counter += 1
                # A collective of rank-derived data still yields
                # rank-dependent output for gather-like ops; keep the
                # conservative flow.
                if in_tainted:
                    taint.update(eqn.outvars)
                continue

            if name in ("cond", "switch"):
                pred = eqn.invars[0]
                pred_tainted = self._tainted(pred, taint)
                branches = eqn.params.get("branches", ())
                site = self.cond_sites
                self.cond_sites += 1
                cond_src = _user_source(eqn.source_info)
                out_tainted = in_tainted
                for b, closed in enumerate(branches):
                    sub = getattr(closed, "jaxpr", closed)
                    sub_taint = set()
                    # Branch operands are eqn.invars[1:], positionally.
                    ops = eqn.invars[1:]
                    for sv, ov in zip(sub.invars, ops):
                        if self._tainted(ov, taint):
                            sub_taint.add(sv)
                    frame = CondFrame(site=site, branch=b,
                                      n_branches=len(branches),
                                      pred_tainted=pred_tainted,
                                      source=cond_src)
                    sub_out = self.walk(
                        sub, bound=bound, taint=sub_taint,
                        path=f"{path}/cond[{b}]",
                        cond_stack=cond_stack + (frame,))
                    out_tainted = out_tainted or bool(sub_out)
                # The selected branch depends on the predicate: a
                # rank-derived predicate makes every output
                # rank-derived.
                if out_tainted or pred_tainted:
                    taint.update(eqn.outvars)
                continue

            subs = []
            for v in eqn.params.values():
                subs.extend(_subjaxprs(v))

            if not subs:
                if in_tainted:
                    taint.update(eqn.outvars)
                continue

            # Higher-order primitive: bind axes for shard_map/pmap,
            # map taint across the boundary.
            sub_bound = bound
            if name == "shard_map":
                sub_bound = bound | set(
                    _mesh_axis_names(eqn.params.get("mesh")))
            elif name in ("xla_pmap", "pmap"):
                ax = eqn.params.get("axis_name")
                if isinstance(ax, str):
                    sub_bound = bound | {ax}

            out_tainted = False
            for sub in subs:
                sub_taint = set()
                if len(sub.invars) == len(eqn.invars):
                    # Positional match (pjit, shard_map, scan): precise.
                    for sv, ov in zip(sub.invars, eqn.invars):
                        if self._tainted(ov, taint):
                            sub_taint.add(sv)
                elif in_tainted:
                    # Unknown layout (while, custom_vjp consts):
                    # conservative — everything in is tainted.
                    sub_taint.update(sub.invars)
                sub_out = self.walk(
                    sub, bound=sub_bound, taint=sub_taint,
                    path=f"{path}/{name}", cond_stack=cond_stack)
                out_tainted = out_tainted or bool(sub_out)
            if out_tainted or in_tainted:
                taint.update(eqn.outvars)

        return {v for v in jaxpr.outvars if self._tainted(v, taint)}


def trace_events(closed_jaxpr, *,
                 bound_axes: Optional[Sequence[str]] = None
                 ) -> List[CollectiveEvent]:
    """Extract the collective-event stream from a ``ClosedJaxpr``.

    ``bound_axes``: axis names already live at the top level (the
    ``axis_env`` the caller traced under); axes bound by ``shard_map``/
    ``pmap`` equations inside are discovered during the walk.
    """
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    bound = frozenset(bound_axes or ())
    w = _Walker(bound)
    w.walk(jaxpr, bound=bound, taint=set(), path="", cond_stack=())
    return w.events

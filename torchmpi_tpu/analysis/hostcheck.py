"""Host-side static analysis: the H rule pack (``hostcheck``).

The trace-time rules (D/P/C/S — :mod:`checker`, :mod:`rules`) see what
jax sees: one traced program.  The recurring bug classes of the *host*
protocol layers never show up there — an eagerly imported off-by-default
module, a ``tm_*`` counter the metric catalog forgot, a ``Config`` field
that drifted out of ``set_config``, a payload seam the fault layer
cannot reach, a lock-order inversion.  Each of those was guarded by one
hand-written subprocess test, or by nothing.  This module replaces them
with one systematic pass:

=====  ==============================================================
rule   checks
=====  ==============================================================
H1     import discipline: no off-by-default subsystem (``analysis``,
       ``obs``, ``faults``, ``elastic``, ``hotstate``, ``guard``,
       ``serving``, ``watchdog``, ``utils.durable``) is reachable in
       the *eager* import closure of ``import torchmpi_tpu`` — only
       through its documented gate (the package ``__getattr__``, a
       ``sys.modules`` probe, or a config-string branch inside a
       function)
H2     telemetry drift: every ``tm_*`` metric emitted in code appears
       in ``docs/OBSERVABILITY.md``, and every metric the catalog
       names is actually emitted
H3     config drift: every ``Config`` field has a ``docs/API.md``
       row; every env-mapped field of an off-by-default subsystem
       family has the any-config env pickup in ``runtime.init`` and a
       ``set_config`` validation/trigger branch
H4     fault-surface coverage: every ``fire()``/``run_site()`` call
       names a site registered in ``faults/inject.py``, and the
       ``docs/FAULTS.md`` site table matches the registry both ways
H5     lock order: the ``with <lock>``/``acquire()`` nesting graph of
       each module is acyclic
=====  ==============================================================

Everything here is **pure AST + text**: no jax import, no
``torchmpi_tpu`` import, no code execution — ``scripts/
lint_collectives.py --host`` loads this file standalone so the lint
itself cannot trip the very import discipline it checks.  Findings
reuse :class:`findings.Finding`, so ``--json`` output is the same
machine-readable stream as the trace-time rules.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


def _load_findings():
    """The findings module: relative when running inside the package,
    loaded by file path when this module is exec'd standalone (the
    no-jax CLI path)."""
    try:
        from . import findings  # type: ignore[no-redef]

        return findings
    except ImportError:
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "findings.py")
        import sys

        name = "_torchmpi_tpu_hostcheck_findings"
        if name in sys.modules:
            return sys.modules[name]
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        assert spec.loader is not None
        # Registered BEFORE exec: dataclass processing looks the module
        # up in sys.modules.
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod


_findings = _load_findings()
Finding = _findings.Finding
ERROR = _findings.ERROR
WARNING = _findings.WARNING
INFO = _findings.INFO
sort_findings = _findings.sort_findings
format_findings = _findings.format_findings
has_errors = _findings.has_errors
max_severity = _findings.max_severity

# The off-by-default subsystems: importing the package must not import
# them (H1), and their Config knob families follow the full
# env-pickup + set_config contract (H3).  Dotted names are relative to
# the package root.
GATED_MODULES = (
    "analysis", "obs", "faults", "elastic", "hotstate", "guard",
    "serving", "watchdog", "utils.durable",
)

# Config-field families owned by the gated subsystems ("fault" covers
# the fault_retries/... knobs next to the "faults" mode switch, "ckpt"
# is the durable-checkpoint surface of utils.durable).
GATED_FIELD_FAMILIES = (
    "analysis", "obs", "faults", "fault", "guard", "watchdog",
    "elastic", "hotstate", "serving", "ckpt",
)

# Registry methods whose first argument is a metric name (obs/__init__
# is the only emitter, but the scan covers the whole package).
_EMIT_FUNCS = ("counter_inc", "hist_observe", "counter_handle",
               "hist_handle")

# Doc tokens that look like metrics but are not registry metric names
# (reviewed by hand; keep this list short and commented).
H2_DOC_IGNORE = frozenset({
    # The PS server's native stats-struct name, mentioned in the
    # tm_ps_{...}_total row's description — not itself a metric.
    "tm_ps_server_stats",
    # Per-stage ladder outcome counters: the bench supervisor writes
    # these BY HAND in the obs dump format (it never imports the
    # package, so they are not registry metrics — see bench.py
    # bank_stage_counters).
    "tm_bench_stage_live_total",
    "tm_bench_stage_banked_total",
    "tm_bench_stage_wedged_total",
})

# Fault-injection wrapper spellings whose first literal argument is a
# site name (faults.fire / membership's self._fire / policy run_site).
_SITE_FUNCS = ("fire", "_fire", "run_site")
_SITE_SHAPE = re.compile(r"^[a-z_]+\.[a-z_]+$")


# --------------------------------------------------------------------
# shared AST plumbing
# --------------------------------------------------------------------

def _iter_py(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _parse(path: str) -> Optional[ast.Module]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return None


def _module_name(pkg_root: str, path: str) -> str:
    """Dotted module name of ``path`` relative to the package root
    (``pkg_root`` names the package directory itself)."""
    pkg = os.path.basename(os.path.normpath(pkg_root))
    rel = os.path.relpath(path, pkg_root)
    parts = rel[:-3].split(os.sep)  # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join([pkg] + [p for p in parts if p])


def _package_modules(pkg_root: str) -> Dict[str, str]:
    return {_module_name(pkg_root, p): p for p in _iter_py(pkg_root)}


def _is_type_checking_if(node: ast.If) -> bool:
    return "TYPE_CHECKING" in ast.dump(node.test)


def _eager_imports(tree: ast.Module, modname: str, is_pkg: bool,
                   known: Set[str], pkg: str) -> List[Tuple[str, int]]:
    """Package-internal modules imported when ``modname`` is imported:
    module-level statements only (functions are the lazy gates), with
    ``if TYPE_CHECKING:`` blocks excluded.  Class bodies and
    module-level ``try``/``if`` blocks DO run at import and count."""
    out: List[Tuple[str, int]] = []

    def resolve_from(node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        anchor = modname if is_pkg else modname.rsplit(".", 1)[0]
        for _ in range(node.level - 1):
            if "." not in anchor:
                return None
            anchor = anchor.rsplit(".", 1)[0]
        return f"{anchor}.{node.module}" if node.module else anchor

    def visit(body) -> None:
        for node in body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == pkg or alias.name.startswith(pkg + "."):
                        out.append((alias.name, node.lineno))
            elif isinstance(node, ast.ImportFrom):
                base = resolve_from(node)
                if base and (base == pkg or base.startswith(pkg + ".")):
                    out.append((base, node.lineno))
                    for alias in node.names:
                        sub = f"{base}.{alias.name}"
                        if sub in known:
                            out.append((sub, node.lineno))
            elif isinstance(node, ast.If):
                if not _is_type_checking_if(node):
                    visit(node.body)
                    visit(node.orelse)
            elif isinstance(node, ast.Try):
                visit(node.body)
                for h in node.handlers:
                    visit(h.body)
                visit(node.orelse)
                visit(node.finalbody)
            elif isinstance(node, ast.ClassDef):
                visit(node.body)
            elif isinstance(node, (ast.With,)):
                visit(node.body)

    visit(tree.body)
    return out


# --------------------------------------------------------------------
# H1 — import discipline
# --------------------------------------------------------------------

def check_imports(pkg_root: str,
                  gated: Sequence[str] = GATED_MODULES) -> List[Finding]:
    """H1: the eager import closure of the package root must not reach
    any gated subsystem."""
    modules = _package_modules(pkg_root)
    pkg = os.path.basename(os.path.normpath(pkg_root))
    known = set(modules)
    if pkg not in modules:
        return []
    graph: Dict[str, List[Tuple[str, int]]] = {}
    for name, path in modules.items():
        tree = _parse(path)
        if tree is None:
            continue
        is_pkg = os.path.basename(path) == "__init__.py"
        imps = _eager_imports(tree, name, is_pkg, known, pkg)
        # A dotted import implies its parent packages.
        full: List[Tuple[str, int]] = []
        for target, line in imps:
            parts = target.split(".")
            for k in range(1, len(parts) + 1):
                prefix = ".".join(parts[:k])
                if prefix in known:
                    full.append((prefix, line))
        graph[name] = full

    # BFS from the package root, keeping one witness chain per module.
    parent: Dict[str, Tuple[str, int]] = {}
    seen = {pkg}
    frontier = [pkg]
    while frontier:
        nxt: List[str] = []
        for mod in frontier:
            for target, line in graph.get(mod, ()):
                if target not in seen:
                    seen.add(target)
                    parent[target] = (mod, line)
                    nxt.append(target)
        frontier = nxt

    gated_full = [f"{pkg}.{g}" for g in gated]
    findings: List[Finding] = []
    for g in gated_full:
        hits = sorted(m for m in seen
                      if m == g or m.startswith(g + "."))
        if not hits:
            continue
        # Report the shallowest reachable module of the subsystem, with
        # its witness import chain.
        mod = hits[0]
        chain = [mod]
        line = 0
        while chain[-1] in parent:
            via, ln = parent[chain[-1]]
            line = line or ln
            chain.append(via)
        chain.reverse()
        importer = chain[-2] if len(chain) > 1 else pkg
        findings.append(Finding(
            rule="H1", severity=ERROR,
            message=(
                f"off-by-default module {mod!r} is in the eager import "
                f"closure of {pkg!r} (chain: {' -> '.join(chain)}); it "
                f"must only load through its gate — the package "
                f"__getattr__, a sys.modules probe, or a config branch "
                f"inside a function"),
            source=f"{modules.get(importer, importer)}:{line}"))
    return findings


# --------------------------------------------------------------------
# H2 — telemetry drift
# --------------------------------------------------------------------

def _fstring_regex(node: ast.JoinedStr) -> str:
    pat = ""
    for v in node.values:
        if isinstance(v, ast.Constant):
            pat += re.escape(str(v.value))
        else:
            pat += r"[a-z0-9_]+"
    return pat


def _emitted_metrics(pkg_root: str):
    """(literal names, {template regex: (file, line, src)}) for every
    registry emit call in the package."""
    lits: Dict[str, Tuple[str, int]] = {}
    templates: Dict[str, Tuple[str, int, str]] = {}
    for path in _iter_py(pkg_root):
        tree = _parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else getattr(fn, "id", ""))
            if name not in _EMIT_FUNCS:
                continue
            a0 = node.args[0]
            if (isinstance(a0, ast.Constant) and isinstance(a0.value, str)
                    and a0.value.startswith("tm_")):
                lits.setdefault(a0.value, (path, node.lineno))
            elif isinstance(a0, ast.JoinedStr):
                src = ast.unparse(a0)
                if "tm_" in src:
                    templates.setdefault(_fstring_regex(a0),
                                         (path, node.lineno, src))
    return lits, templates


_DOC_TOKEN = re.compile(
    r"tm_[a-z0-9_]*(?:\{[a-z0-9_,]+\}[a-z0-9_]+)*(?:\{[a-z0-9_,]+\})?")


def _doc_metric_tokens(text: str) -> Set[str]:
    """``tm_*`` names in the catalog, with ``{a,b,c}`` mid-name groups
    expanded and a trailing ``{label,...}`` annotation stripped."""
    tokens: Set[str] = set()
    for m in _DOC_TOKEN.finditer(text):
        t = re.sub(r"\{[a-z0-9_,]+\}$", "", m.group(0))
        outs = [""]
        for part in re.split(r"(\{[a-z0-9_,]+\})", t):
            if part.startswith("{"):
                outs = [o + alt for o in outs
                        for alt in part[1:-1].split(",")]
            else:
                outs = [o + part for o in outs]
        tokens.update(o for o in outs if len(o) > len("tm_"))
    return tokens


def check_telemetry(pkg_root: str, docs_root: str) -> List[Finding]:
    """H2: code-emitted ``tm_*`` metrics vs the docs/OBSERVABILITY.md
    catalog, both directions."""
    doc_path = os.path.join(docs_root, "OBSERVABILITY.md")
    try:
        with open(doc_path, "r", encoding="utf-8") as fh:
            tokens = _doc_metric_tokens(fh.read())
    except OSError:
        tokens = set()
    lits, templates = _emitted_metrics(pkg_root)
    findings: List[Finding] = []
    for name, (path, line) in sorted(lits.items()):
        if name not in tokens:
            findings.append(Finding(
                rule="H2", severity=ERROR,
                message=(f"metric {name!r} is emitted but missing from "
                         f"docs/OBSERVABILITY.md's catalog"),
                source=f"{path}:{line}"))
    for pat, (path, line, src) in sorted(templates.items()):
        if not any(re.fullmatch(pat, t) for t in tokens):
            findings.append(Finding(
                rule="H2", severity=ERROR,
                message=(f"metric family {src} has no instantiation in "
                         f"docs/OBSERVABILITY.md's catalog"),
                source=f"{path}:{line}"))
    for t in sorted(tokens - set(lits) - H2_DOC_IGNORE):
        if any(re.fullmatch(p, t) for p in templates):
            continue
        findings.append(Finding(
            rule="H2", severity=ERROR,
            message=(f"docs/OBSERVABILITY.md documents {t!r} but no "
                     f"code emits it"),
            source=doc_path))
    return findings


# --------------------------------------------------------------------
# H3 — config drift
# --------------------------------------------------------------------

def _config_surface(pkg_root: str):
    """(ordered Config fields, field -> env var from ``from_env``)."""
    tree = _parse(os.path.join(pkg_root, "config.py"))
    fields: List[str] = []
    env: Dict[str, str] = {}
    if tree is None:
        return fields, env
    cls = next((n for n in tree.body
                if isinstance(n, ast.ClassDef) and n.name == "Config"),
               None)
    if cls is None:
        return fields, env

    def env_of(call: ast.AST) -> Optional[str]:
        for node in ast.walk(call):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value.startswith("TORCHMPI_TPU_"):
                return node.value
        return None

    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                          ast.Name):
            fields.append(stmt.target.id)
        if isinstance(stmt, ast.FunctionDef) and stmt.name == "from_env":
            for node in ast.walk(stmt):
                if isinstance(node, ast.keyword) and node.arg in fields:
                    name = env_of(node.value)
                    if name:
                        env[node.arg] = name
                # The tail `cfg.field = ...os.environ.get("X")...` form.
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Attribute):
                    name = env_of(node.value)
                    if name:
                        env.setdefault(node.targets[0].attr, name)
    return fields, env


def _set_config_literals(runtime_tree: ast.Module) -> Set[str]:
    fn = next((n for n in runtime_tree.body
               if isinstance(n, ast.FunctionDef)
               and n.name == "set_config"), None)
    if fn is None:
        return set()
    return {node.value for node in ast.walk(fn)
            if isinstance(node, ast.Constant)
            and isinstance(node.value, str)}


def check_config(pkg_root: str, docs_root: str) -> List[Finding]:
    """H3: Config fields vs their three host surfaces — the API.md
    table (every field), and for the gated-subsystem knob families the
    any-config env pickup in ``runtime.init`` plus a ``set_config``
    branch."""
    fields, env_map = _config_surface(pkg_root)
    if not fields:
        return []
    runtime_path = os.path.join(pkg_root, "runtime.py")
    runtime_tree = _parse(runtime_path)
    if runtime_tree is None:
        return []
    with open(runtime_path, "r", encoding="utf-8") as fh:
        runtime_envs = set(re.findall(r"TORCHMPI_TPU_[A-Z0-9_]+",
                                      fh.read()))
    sc_lits = _set_config_literals(runtime_tree)
    try:
        with open(os.path.join(docs_root, "API.md"), "r",
                  encoding="utf-8") as fh:
            api = fh.read()
    except OSError:
        api = ""

    findings: List[Finding] = []
    config_path = os.path.join(pkg_root, "config.py")
    for f in fields:
        if f"`{f}`" not in api and f"Config.{f}" not in api:
            findings.append(Finding(
                rule="H3", severity=ERROR,
                message=f"Config.{f} has no docs/API.md table row",
                source=config_path))
        if f.split("_")[0] not in GATED_FIELD_FAMILIES:
            continue
        env = env_map.get(f)
        if env and env not in runtime_envs:
            findings.append(Finding(
                rule="H3", severity=ERROR,
                message=(
                    f"Config.{f} maps to {env} in Config.from_env but "
                    f"runtime.init never picks it up for an explicit "
                    f"config (the any-config _env_default_pickup "
                    f"contract its subsystem siblings follow)"),
                source=runtime_path))
        if f not in sc_lits:
            findings.append(Finding(
                rule="H3", severity=ERROR,
                message=(
                    f"Config.{f} has no set_config validation or "
                    f"activation branch — a runtime switch of it is "
                    f"applied unchecked"),
                source=runtime_path))
    return findings


# --------------------------------------------------------------------
# H4 — fault-surface coverage
# --------------------------------------------------------------------

def _registered_sites(pkg_root: str) -> Set[str]:
    tree = _parse(os.path.join(pkg_root, "faults", "inject.py"))
    if tree is None:
        return set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "SITES":
            return {elt.value for elt in ast.walk(node.value)
                    if isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)}
    return set()


def check_faults(pkg_root: str, docs_root: str) -> List[Finding]:
    """H4: every literal ``fire()``/``run_site()`` site exists in the
    ``SITES`` registry, and the docs/FAULTS.md site table matches the
    registry in both directions."""
    sites = _registered_sites(pkg_root)
    inject_path = os.path.join(pkg_root, "faults", "inject.py")
    if not sites:
        return []
    findings: List[Finding] = []
    for path in _iter_py(pkg_root):
        if path == inject_path:
            continue
        tree = _parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else getattr(fn, "id", ""))
            if name not in _SITE_FUNCS:
                continue
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str) \
                    and _SITE_SHAPE.match(a0.value) \
                    and a0.value not in sites:
                findings.append(Finding(
                    rule="H4", severity=ERROR,
                    message=(
                        f"{name}({a0.value!r}) targets a site missing "
                        f"from faults/inject.py SITES — the seam is "
                        f"invisible to every fault plan"),
                    source=f"{path}:{node.lineno}"))
    doc_path = os.path.join(docs_root, "FAULTS.md")
    try:
        with open(doc_path, "r", encoding="utf-8") as fh:
            doc = fh.read()
    except OSError:
        doc = ""
    doc_sites = {m.group(1)
                 for m in re.finditer(r"^\|\s*`([a-z_]+\.[a-z_]+)`",
                                      doc, re.M)}
    for s in sorted(doc_sites - sites):
        findings.append(Finding(
            rule="H4", severity=ERROR,
            message=(f"docs/FAULTS.md documents site {s!r} which is "
                     f"not registered in faults/inject.py SITES"),
            source=doc_path))
    for s in sorted(sites - doc_sites):
        if doc:
            findings.append(Finding(
                rule="H4", severity=ERROR,
                message=(f"site {s!r} is registered in faults/inject.py "
                         f"but missing from the docs/FAULTS.md site "
                         f"table"),
                source=inject_path))
    return findings


# --------------------------------------------------------------------
# H5 — lock order
# --------------------------------------------------------------------

def _lockish(expr: ast.AST) -> Optional[str]:
    """A lock-identity key for a with/acquire target, or None.  Keys
    are textual per module; ``self.X`` is qualified by the enclosing
    class later."""
    target = expr
    # with lock.acquire() / lock.acquire(timeout=...) — unwrap the call
    if isinstance(target, ast.Call) and isinstance(target.func,
                                                   ast.Attribute) \
            and target.func.attr == "acquire":
        target = target.func.value
    if isinstance(target, (ast.Name, ast.Attribute)):
        tail = target.attr if isinstance(target, ast.Attribute) \
            else target.id
        if "lock" in tail.lower():
            try:
                return ast.unparse(target)
            except Exception:  # noqa: BLE001
                return None
    return None


def _module_lock_edges(tree: ast.Module):
    """Directed edges (outer held -> inner acquired), with one witness
    line per edge."""
    edges: Dict[Tuple[str, str], int] = {}

    def key(name: str, cls: Optional[str]) -> str:
        return f"{cls}.{name}" if cls and name.startswith("self.") \
            else name

    def visit(node, held: Tuple[str, ...], cls: Optional[str]) -> None:
        if isinstance(node, ast.ClassDef):
            cls = node.name
        acquired: List[str] = []
        if isinstance(node, ast.With):
            for item in node.items:
                lk = _lockish(item.context_expr)
                if lk:
                    acquired.append(key(lk, cls))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "acquire":
            lk = _lockish(node)
            if lk:
                held_k = key(lk, cls)
                for h in held:
                    if h != held_k:
                        edges.setdefault((h, held_k), node.lineno)
        for a in acquired:
            for h in held:
                if h != a:
                    edges.setdefault((h, a), node.lineno)
        inner = held + tuple(acquired)
        for child in ast.iter_child_nodes(node):
            # A nested def runs later, under whatever locks its CALLER
            # holds — not the ones held at definition site.
            child_held = () if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.Lambda)) else inner
            visit(child, child_held, cls)

    visit(tree, (), None)
    return edges


def _find_cycle(edges) -> Optional[List[str]]:
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GREY
        stack.append(n)
        for m in graph.get(n, ()):
            c = color.get(m, WHITE)
            if c == GREY:
                return stack[stack.index(m):] + [m]
            if c == WHITE:
                cyc = dfs(m)
                if cyc:
                    return cyc
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color.get(n, WHITE) == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None


def check_locks(pkg_root: str) -> List[Finding]:
    """H5: per-module lock-acquisition graphs must be acyclic.  Lock
    identity is textual (``self._lock`` qualified by class), so the
    check is per module — exactly the scope where the planner table,
    obs registry, hotstate store, and membership board locks live."""
    findings: List[Finding] = []
    for path in _iter_py(pkg_root):
        tree = _parse(path)
        if tree is None:
            continue
        edges = _module_lock_edges(tree)
        if not edges:
            continue
        cyc = _find_cycle(edges)
        if cyc:
            line = min(ln for (a, b), ln in edges.items()
                       if a in cyc and b in cyc)
            findings.append(Finding(
                rule="H5", severity=ERROR,
                message=(
                    f"lock-order cycle {' -> '.join(cyc)}: two threads "
                    f"taking these locks in different orders can "
                    f"deadlock"),
                source=f"{path}:{line}"))
    return findings


# --------------------------------------------------------------------
# entry
# --------------------------------------------------------------------

HOST_RULES = {
    "H1": "off-by-default module imported outside its documented gate",
    "H2": "tm_* metric catalog drift between code and "
          "docs/OBSERVABILITY.md",
    "H3": "Config field missing API.md row / env pickup / set_config "
          "branch",
    "H4": "fault-injection site drift between call sites, "
          "faults/inject.py and docs/FAULTS.md",
    "H5": "lock-order cycle inside a module",
}


def run_hostcheck(package_root: Optional[str] = None,
                  docs_root: Optional[str] = None,
                  rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the H rule pack; returns sorted findings.

    ``package_root`` is the package *directory* (default: the
    ``torchmpi_tpu`` tree this file lives in); ``docs_root`` the docs
    directory next to it.  Both are parameters so the rule fixtures
    can point the pass at synthetic trees."""
    if package_root is None:
        package_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
    if docs_root is None:
        docs_root = os.path.join(os.path.dirname(package_root), "docs")
    selected = set(rules) if rules is not None else set(HOST_RULES)
    out: List[Finding] = []
    if "H1" in selected:
        out.extend(check_imports(package_root))
    if "H2" in selected:
        out.extend(check_telemetry(package_root, docs_root))
    if "H3" in selected:
        out.extend(check_config(package_root, docs_root))
    if "H4" in selected:
        out.extend(check_faults(package_root, docs_root))
    if "H5" in selected:
        out.extend(check_locks(package_root))
    return sort_findings(out)


__all__ = [
    "run_hostcheck", "check_imports", "check_telemetry", "check_config",
    "check_faults", "check_locks", "HOST_RULES", "GATED_MODULES",
    "Finding", "format_findings", "has_errors", "max_severity",
]

"""Static SPMD collective-consistency analysis.

TorchMPI's collectives were correct by construction — one communicator
tree, one call order.  The jax_graft port's correctness instead depends
on every ``*_in_axis`` call site agreeing across ranks: a rank-divergent
branch or a shadowed axis name compiles fine and then deadlocks a
v5e-64 pod.  This package is the static checker for that class of bug:
trace a step function to a jaxpr (no device execution), walk it
recursively through ``pjit``/``shard_map``/``scan``/``cond``/
``custom_vjp`` sub-jaxprs into a stream of collective events, and run a
rule registry over the stream.

Surfaces:

- :func:`check` — ``check(fn, *args)`` returns structured
  :class:`Finding`\\ s (rule id, severity, jaxpr path, source
  provenance).
- :func:`assert_clean` — the pytest helper; raises on error-severity
  findings.
- ``scripts/lint_collectives.py`` — the CLI (``--json``, exit nonzero
  on errors).
- ``Config.analysis="warn"|"error"`` (env ``TORCHMPI_TPU_ANALYSIS``) —
  opt-in runtime hook: the checker runs once per jit-cache entry inside
  the eager collectives and the step builders.  Off by default; when
  off there is zero added cost.

Rule catalog: see :mod:`torchmpi_tpu.analysis.rules` and
``docs/ANALYSIS.md``.
"""

from .findings import (  # noqa: F401
    ERROR,
    WARNING,
    INFO,
    Finding,
    format_findings,
    has_errors,
    max_severity,
    sort_findings,
)
from .events import CollectiveEvent, CondFrame, trace_events  # noqa: F401
from .rules import (  # noqa: F401
    RULES,
    P1_MIN_COUNT,
    P2_MIN_NBYTES,
    Rule,
    RuleContext,
    register_rule,
    rule_catalog,
    run_rules,
)
from .checker import assert_clean, check, check_jaxpr, trace_fn  # noqa: F401
from .hostcheck import (  # noqa: F401
    GATED_MODULES,
    HOST_RULES,
    run_hostcheck,
)
from .slices import SliceEvent, trace_slice_events  # noqa: F401
from .hook import (  # noqa: F401
    AnalysisError,
    ANALYSIS_OUT_ENV,
    arm_runtime_capture,
    captured_findings,
    check_once,
    report,
    reset_captured,
    wrap_step,
)

def lint_full(package_root=None, docs_root=None, rules=None):
    """Pytest/CI helper: run the host-side H rule pack
    (:mod:`hostcheck` — pure AST, no tracing, fast) and raise
    ``AssertionError`` on error-severity findings, mirroring
    :func:`assert_clean` for the trace-time rules.  Returns the full
    finding list."""
    findings = run_hostcheck(package_root, docs_root, rules=rules)
    bad = [f for f in findings if f.severity == ERROR]
    if bad:
        raise AssertionError(
            f"host-side static analysis found {len(bad)} problem(s):\n"
            f"{format_findings(bad)}")
    return findings


__all__ = [
    "ERROR", "WARNING", "INFO", "Finding", "format_findings",
    "has_errors", "max_severity", "sort_findings",
    "CollectiveEvent", "CondFrame", "trace_events",
    "RULES", "Rule", "RuleContext", "register_rule", "rule_catalog",
    "run_rules", "P1_MIN_COUNT", "P2_MIN_NBYTES",
    "assert_clean", "check", "check_jaxpr", "trace_fn",
    "AnalysisError", "ANALYSIS_OUT_ENV", "arm_runtime_capture",
    "captured_findings", "check_once", "report",
    "reset_captured", "wrap_step",
    "GATED_MODULES", "HOST_RULES", "run_hostcheck", "lint_full",
    "SliceEvent", "trace_slice_events",
]

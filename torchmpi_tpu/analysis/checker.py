"""The analyzer entry points: trace, walk, run rules, report.

``check(fn, *args)`` is the whole pipeline: trace ``fn`` to a
``ClosedJaxpr`` (``jax.make_jaxpr`` — the compat-shimmed jax surface of
``utils/jaxcompat.py`` applies), walk it into a collective-event stream
(:mod:`events`), collect the trace-time fusion/ZeRO layout records, and
run the rule registry (:mod:`rules`).  Everything is trace-time only:
nothing here ever runs device code or touches the step's runtime cost.

``assert_clean`` is the pytest-facing wrapper; the opt-in runtime hook
(``Config.analysis``) lives in :mod:`torchmpi_tpu.analysis.hook`.
"""

from __future__ import annotations

import sys
from typing import Any, List, Optional, Sequence, Tuple

from .events import trace_events
from .findings import (ERROR, Finding, format_findings, has_errors,
                       severity_rank, sort_findings)
from .rules import RuleContext, run_rules, unbound_axis_finding

AxisEnv = Sequence[Tuple[str, int]]


def _effective_config(config):
    if config is not None:
        return config
    from .. import runtime

    return runtime.effective_config()


def _capture_records(records: List[dict]):
    """Listener installed on the fusion layer during tracing: every
    fused-collective / ZeRO layout record lands in ``records``."""
    def listen(rec: dict) -> None:
        records.append(rec)
    return listen


def trace_fn(fn, *args, axis_env: Optional[AxisEnv] = None,
             _records_out: Optional[List[dict]] = None,
             **kwargs) -> Tuple[Any, List[dict]]:
    """Trace ``fn`` to a ClosedJaxpr, collecting fusion/ZeRO records.

    Raises whatever tracing raises — ``check`` is the surface that
    converts unbound-axis failures into findings.  ``_records_out``
    (internal) receives the records captured BEFORE a trace failure, so
    ``check`` can still report record-only rules (C2's residual
    mismatch emits its record and then raises)."""
    import jax

    from .. import fusion

    records: List[dict] = [] if _records_out is None else _records_out
    prev = fusion.set_trace_listener(_capture_records(records))
    try:
        closed = jax.make_jaxpr(
            fn, axis_env=list(axis_env) if axis_env else None
        )(*args, **kwargs)
    finally:
        fusion.set_trace_listener(prev)
    return closed, records


def _is_unbound_axis_error(exc: BaseException) -> bool:
    msg = str(exc)
    return ("unbound axis name" in msg
            or "axis name" in msg and "not found" in msg
            or "is not bound" in msg)


def check(fn, *args, rules: Optional[Sequence[str]] = None,
          axis_env: Optional[AxisEnv] = None, config=None,
          label: str = "", **kwargs) -> List[Finding]:
    """Statically analyze one step function; returns sorted findings.

    ``fn`` is traced with ``jax.make_jaxpr`` on ``args`` (arrays or
    ``jax.ShapeDtypeStruct``s — no device execution happens).  Trace it
    the way it runs: a function that calls ``shard_map`` itself needs no
    extras; per-device code written for use *inside* ``shard_map`` needs
    ``axis_env=[("axis", size), ...]`` to bind its axis names.

    ``rules`` selects a subset of the registry (default: all).
    ``config`` overrides the effective runtime config consulted by the
    perf rules.  A trace failure caused by an unbound axis name is
    converted into the D2 finding it really is; other trace errors
    propagate.
    """
    partial: List[dict] = []
    try:
        closed, records = trace_fn(fn, *args, axis_env=axis_env,
                                   _records_out=partial, **kwargs)
    except NameError as e:
        # Convert only when the caller selected D2 (or ran all rules):
        # with D2 excluded, fabricating the finding would sneak an
        # unselected rule past assert_clean — re-raise instead, which
        # also keeps the trace failure loud rather than hidden.
        if _is_unbound_axis_error(e) and (rules is None or "D2" in rules):
            return [unbound_axis_finding(e, label)]
        raise
    except ValueError as e:
        # A structural-validation raise mid-trace: the EF residual
        # mismatch (gradsync/zero — docs/HIERARCHICAL.md) emits its C2
        # record BEFORE raising, so the captured records can still name
        # the site with provenance the bare exception lacks.  Only that
        # exact raise converts (compress.ResidualMismatchError, looked
        # up via sys.modules so analysis never imports the codec
        # module): a generic ValueError later in a trace that earlier
        # caught-and-survived a mismatch must propagate loud, not be
        # masked by the stale record.
        _codec = sys.modules.get("torchmpi_tpu.compress")
        if (_codec is not None
                and isinstance(e, _codec.ResidualMismatchError)
                and (rules is None or "C2" in rules)):
            ctx = RuleContext(
                events=(),
                records=[r for r in partial
                         if r.get("kind") == "dcn_residual"],
                config=_effective_config(config), label=label)
            found = [f for f in run_rules(ctx, ("C2",))
                     if f.severity == ERROR]
            if found:
                return sort_findings(found)
        raise
    bound = [a for a, _ in (axis_env or ())]
    return check_jaxpr(closed, records=records, bound_axes=bound,
                       rules=rules, config=config, label=label)


def check_jaxpr(closed_jaxpr, *, records: Sequence[dict] = (),
                bound_axes: Sequence[str] = (),
                rules: Optional[Sequence[str]] = None,
                config=None, label: str = "") -> List[Finding]:
    """Run the rules over an already-traced ClosedJaxpr."""
    from .slices import trace_slice_events

    events = trace_events(closed_jaxpr, bound_axes=bound_axes)
    ctx = RuleContext(events=events, records=list(records),
                      config=_effective_config(config), label=label,
                      slice_events=trace_slice_events(closed_jaxpr))
    return sort_findings(run_rules(ctx, rules))


def assert_clean(fn, *args, rules: Optional[Sequence[str]] = None,
                 axis_env: Optional[AxisEnv] = None, config=None,
                 fail_on: str = ERROR, label: str = "",
                 **kwargs) -> List[Finding]:
    """Pytest helper: run :func:`check` and raise ``AssertionError`` if
    any finding is at least ``fail_on`` severe (default: errors only —
    perf warnings don't fail a correctness suite).  Returns the full
    finding list so callers can still inspect the quieter ones."""
    findings = check(fn, *args, rules=rules, axis_env=axis_env,
                     config=config, label=label, **kwargs)
    threshold = severity_rank(fail_on)
    bad = [f for f in findings if severity_rank(f.severity) <= threshold]
    if bad:
        raise AssertionError(
            f"collective-consistency analysis of "
            f"{label or getattr(fn, '__name__', fn)!r} found "
            f"{len(bad)} problem(s):\n{format_findings(bad)}")
    return findings


__all__ = [
    "check", "check_jaxpr", "assert_clean", "trace_fn",
    "Finding", "format_findings", "has_errors",
]

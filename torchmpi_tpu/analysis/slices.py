"""Trace-time dynamic-slice safety analysis (the S rules' event stream).

Walks a ClosedJaxpr — through ``pjit``/``shard_map``/``scan``/``cond``
and the other higher-order primitives — and emits one
:class:`SliceEvent` per ``dynamic_update_slice`` / ``dynamic_slice`` /
batched-write ``scatter`` equation, carrying whether the start indices
are *provably in bounds* for the update width.  This is the static
form of the PR 17 slot-cache hazard: ``dynamic_update_slice`` CLAMPS an
out-of-range start instead of failing, so an unclamped data-dependent
write index silently corrupts the last cache rows (see the comment in
``models/transformer.py``'s decode path).  ``jax.vmap`` lowers the
per-row form to a ``scatter`` with ``mode=CLIP`` — the same silent
clamp — so both spellings are covered.

The proof is a forward interval analysis over the integer scalars that
feed start operands: literals, ``iota``, ``clamp``/``min``/``max``
(what ``jnp.clip`` lowers to, inside a ``pjit[name=clip]`` call),
``add``/``sub``, ``rem``, and the ``select_n(lt(x, 0), x, x + dim)``
negative-index normalization jax inserts around every dynamic slice.
Anything the analysis cannot bound is treated as unbounded — a clamp
the checker cannot see is a clamp a reviewer cannot see either.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .events import _subjaxprs, _user_source

NEG = float("-inf")
POS = float("inf")
TOP = (NEG, POS)

# Primitives whose output interval is the (elementwise) input interval.
_PASSTHROUGH = (
    "convert_element_type", "copy", "stop_gradient", "broadcast_in_dim",
    "reshape", "squeeze", "expand_dims", "transpose", "rev",
    "reduce_min", "reduce_max", "device_put", "optimization_barrier",
)

_SLICE_PRIMS = ("dynamic_update_slice", "dynamic_slice", "scatter")


@dataclass(frozen=True)
class SliceEvent:
    """One dynamic-slice-family equation found in the trace."""

    op: str            # dynamic_update_slice | dynamic_slice | scatter
    path: str          # nesting path, e.g. "pjit/scan"
    source: str        # user call site ("file.py:line (fn)"), best-effort
    write: bool        # update/scatter (True) vs read (False)
    batched: bool      # per-row (vmap-lowered scatter) form
    on_buffer: bool    # operand is an outer input or a scan carry
    data_dependent: bool  # some start index derives from traced data
    safe: bool         # every start provably leaves room for the width
    detail: str = ""   # first failing dim: interval vs room


def _is_lit(a) -> bool:
    return hasattr(a, "val")


def _lit_iv(a) -> Tuple[float, float]:
    """Interval of a literal (or concrete const) value, if integral."""
    v = a.val if hasattr(a, "val") else a
    try:
        import numpy as np

        arr = np.asarray(v)
        if arr.dtype.kind in "iu" and arr.size:
            return (float(arr.min()), float(arr.max()))
    except Exception:  # noqa: BLE001 — unbounded is always sound
        pass
    return TOP


class _SliceWalker:
    """Single forward pass (SSA order) accumulating interval facts,
    data-dependence bits, buffer-ness, and slice events."""

    def __init__(self) -> None:
        self.iv: Dict[int, Tuple[float, float]] = {}
        self.data: Set[int] = set()
        self.parts: Dict[int, List[Any]] = {}  # concatenate components
        self.buffers: Set[int] = set()
        self.events: List[SliceEvent] = []
        # ``lt(x, 0)`` predicates seen so far (pred-var id -> x), for
        # the select_n dead-branch refinement.
        self._lt_pred: Dict[int, Any] = {}

    # -- fact lookups -------------------------------------------------

    def _aiv(self, a) -> Tuple[float, float]:
        if _is_lit(a):
            return _lit_iv(a)
        return self.iv.get(id(a), TOP)

    def _adata(self, a) -> bool:
        return (not _is_lit(a)) and id(a) in self.data

    def _abuf(self, a) -> bool:
        return (not _is_lit(a)) and id(a) in self.buffers

    def _set(self, v, iv: Tuple[float, float], data: bool) -> None:
        self.iv[id(v)] = iv
        if data:
            self.data.add(id(v))

    # -- entry --------------------------------------------------------

    def walk_closed(self, closed) -> List[SliceEvent]:
        jaxpr = closed.jaxpr
        for cv, c in zip(jaxpr.constvars, closed.consts):
            self._set(cv, _lit_iv(c), data=False)
        for v in jaxpr.invars:
            self._set(v, TOP, data=True)
            self.buffers.add(id(v))
        self._walk(jaxpr, path="")
        return self.events

    # -- recursion ----------------------------------------------------

    def _unwrap(self, s):
        """A sub-jaxpr as emitted (Jaxpr or ClosedJaxpr): return the
        raw jaxpr, seeding constvar facts from closed-over consts —
        dropping them would turn a folded clamp bound into unbounded."""
        if hasattr(s, "jaxpr"):
            for cv, c in zip(s.jaxpr.constvars, getattr(s, "consts", ())):
                self._set(cv, _lit_iv(c), data=False)
            return s.jaxpr
        return s

    def _map_into(self, outer_atoms, inner_vars, *,
                  carry_buffers: Sequence[int] = ()) -> None:
        """Seed a sub-jaxpr's invars from the caller's operands (when
        the arities line up — conservative TOP otherwise)."""
        if len(outer_atoms) == len(inner_vars):
            for o, i in zip(outer_atoms, inner_vars):
                self._set(i, self._aiv(o), self._adata(o))
                if self._abuf(o):
                    self.buffers.add(id(i))
        else:
            for i in inner_vars:
                self._set(i, TOP, data=True)
        for idx in carry_buffers:
            if idx < len(inner_vars):
                self.buffers.add(id(inner_vars[idx]))

    def _map_out(self, inner_outs, outer_outs) -> None:
        if len(inner_outs) == len(outer_outs):
            for i, o in zip(inner_outs, outer_outs):
                self._set(o, self._aiv(i), self._adata(i))

    def _walk(self, jaxpr, path: str) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in _SLICE_PRIMS:
                self._slice_eqn(eqn, name, path)
                # The updated buffer stays a buffer: later writes to the
                # result of this write are still cache writes.
                if name != "dynamic_slice" and eqn.outvars \
                        and self._abuf(eqn.invars[0]):
                    self.buffers.add(id(eqn.outvars[0]))
            subs = [s for v in eqn.params.values() for s in _subjaxprs(v)]
            if subs:
                self._call_eqn(eqn, name, subs, path)
            elif name not in _SLICE_PRIMS:
                self._transfer(eqn, name)

    def _call_eqn(self, eqn, name: str, subs, path: str) -> None:
        sub_path = f"{path}/{name}" if path else name
        if name == "scan":
            nc = int(eqn.params.get("num_consts", 0))
            ncar = int(eqn.params.get("num_carry", 0))
            sub = self._unwrap(subs[0])
            # Consts map through; carries and xs are loop-varying, so
            # their intervals are unbounded — but a carry IS a candidate
            # cache buffer (the PR 17 shape: cache carried by the decode
            # scan), and an xs/carry slot fed from a buffer stays one.
            if len(sub.invars) == len(eqn.invars):
                for k, (o, i) in enumerate(zip(eqn.invars, sub.invars)):
                    loopy = k >= nc
                    self._set(i, TOP if loopy else self._aiv(o),
                              data=True if loopy else self._adata(o))
                    if self._abuf(o) or nc <= k < nc + ncar:
                        self.buffers.add(id(i))
            else:
                for i in sub.invars:
                    self._set(i, TOP, data=True)
            self._walk(sub, sub_path)
            return
        if name in ("cond", "switch"):
            # Operands after the predicate map positionally into every
            # branch (the events.py convention).
            ops = eqn.invars[1:]
            for s in subs:
                sub = self._unwrap(s)
                self._map_into(ops, sub.invars)
                self._walk(sub, sub_path)
            return
        if name in ("while",):
            for s in subs:
                sub = self._unwrap(s)
                for i in sub.invars:
                    self._set(i, TOP, data=True)
                self._walk(sub, sub_path)
            return
        # pjit / closed_call / shard_map / pmap / custom_* / remat:
        # positional operand mapping, outvars mapped back.
        for s in subs:
            sub = self._unwrap(s)
            self._map_into(eqn.invars, sub.invars)
            self._walk(sub, sub_path)
            self._map_out(sub.outvars, eqn.outvars)

    # -- interval transfer --------------------------------------------

    def _transfer(self, eqn, name: str) -> None:
        out = eqn.outvars[0] if eqn.outvars else None
        if out is None:
            return
        a = eqn.invars
        if name == "lt" and len(a) == 2 and _is_lit(a[1]) \
                and _lit_iv(a[1]) == (0.0, 0.0) and not _is_lit(a[0]):
            self._lt_pred[id(out)] = a[0]
        dd = any(self._adata(x) for x in a)
        if name in _PASSTHROUGH:
            self._set(out, self._aiv(a[0]), dd)
        elif name == "iota":
            dim = int(eqn.params.get("dimension", 0))
            size = out.aval.shape[dim] if out.aval.shape else 1
            self._set(out, (0.0, float(max(0, size - 1))), False)
        elif name == "add":
            (l1, h1), (l2, h2) = self._aiv(a[0]), self._aiv(a[1])
            self._set(out, (l1 + l2, h1 + h2), dd)
        elif name == "sub":
            (l1, h1), (l2, h2) = self._aiv(a[0]), self._aiv(a[1])
            self._set(out, (l1 - h2, h1 - l2), dd)
        elif name == "max":
            (l1, h1), (l2, h2) = self._aiv(a[0]), self._aiv(a[1])
            self._set(out, (max(l1, l2), max(h1, h2)), dd)
        elif name == "min":
            (l1, h1), (l2, h2) = self._aiv(a[0]), self._aiv(a[1])
            self._set(out, (min(l1, l2), min(h1, h2)), dd)
        elif name == "clamp":  # clamp(lo, x, hi)
            (ll, _lh), (xl, xh) = self._aiv(a[0]), self._aiv(a[1])
            (_hl, hh) = self._aiv(a[2])
            self._set(out, (max(xl, ll), min(xh, hh)), dd)
        elif name == "mul":
            ivs = [self._aiv(x) for x in a]
            lits = [x for x in a if _is_lit(x)]
            if lits and _lit_iv(lits[0])[0] >= 0:
                k = _lit_iv(lits[0])[0]
                other = ivs[1] if _is_lit(a[0]) else ivs[0]
                self._set(out, (other[0] * k, other[1] * k), dd)
            else:
                self._set(out, TOP, dd)
        elif name == "rem":
            (xl, _xh), (dl, dh) = self._aiv(a[0]), self._aiv(a[1])
            if dl == dh and dl > 0 and dl != POS:
                lo = 0.0 if xl >= 0 else -(dl - 1)
                self._set(out, (lo, dl - 1), dd)
            else:
                self._set(out, TOP, dd)
        elif name in ("lt", "le", "gt", "ge"):
            # Boolean interval; decidable comparisons fold to a constant
            # so the select_n negative-index normalization over static
            # indices (``xs[:, -1]`` → ``select_n(lt(-1, 0), ...)``)
            # resolves instead of widening to the union.
            (l1, h1), (l2, h2) = self._aiv(a[0]), self._aiv(a[1])
            if name in ("gt", "ge"):  # a cmp b  ==  b cmp' a
                (l1, h1), (l2, h2) = (l2, h2), (l1, h1)
                name = "lt" if name == "gt" else "le"
            strict = name == "lt"
            if (h1 < l2) or (not strict and h1 == l2):
                self._set(out, (1.0, 1.0), dd)
            elif (l1 > h2) or (strict and l1 == h2):
                self._set(out, (0.0, 0.0), dd)
            else:
                self._set(out, (0.0, 1.0), dd)
        elif name == "select_n":
            self._select_n(eqn, out, dd)
        elif name == "concatenate":
            self._concat(eqn, out, dd)
        else:
            self._set(out, TOP, dd)

    def _select_n(self, eqn, out, dd: bool) -> None:
        """Union of the branch intervals — refined twice: a literal
        predicate selects its branch outright (jit emits unfolded
        ``select_n`` over literals), and the
        ``select_n(lt(x, 0), x, x + D)`` negative-index normalization
        has a dead wrap branch when ``x`` is provably non-negative."""
        pred, *branches = eqn.invars
        if _is_lit(pred):
            try:
                import numpy as np

                k = int(bool(np.asarray(pred.val).flat[0]))
                self._set(out, self._aiv(branches[min(k,
                          len(branches) - 1)]), dd)
                return
            except Exception:  # noqa: BLE001 — fall through to union
                pass
        ivs = [self._aiv(b) for b in branches]
        plo, phi = self._aiv(pred)
        if plo == phi and plo in (0.0, 1.0):
            # Folded comparison predicate (see the cmp transfer above).
            self._set(out, ivs[min(int(plo), len(branches) - 1)], dd)
            return
        lt = self._lt_pred.get(id(pred)) if not _is_lit(pred) else None
        if (lt is not None and len(branches) == 2
                and branches[0] is lt and self._aiv(lt)[0] >= 0):
            self._set(out, self._aiv(branches[0]), dd)
            return
        self._set(out, (min(i[0] for i in ivs), max(i[1] for i in ivs)),
                  dd)

    def _concat(self, eqn, out, dd: bool) -> None:
        ivs = [self._aiv(x) for x in eqn.invars]
        self._set(out, (min(i[0] for i in ivs), max(i[1] for i in ivs)),
                  dd)
        # Component provenance for scatter index vectors: record each
        # operand once per unit it contributes along the concat dim.
        dim = int(eqn.params.get("dimension", 0))
        comps: List[Any] = []
        for x in eqn.invars:
            shape = getattr(getattr(x, "aval", None), "shape", ())
            n = int(shape[dim]) if dim < len(shape) else 1
            comps.extend([x] * max(1, n))
        self.parts[id(out)] = comps

    # -- slice checks -------------------------------------------------

    def _slice_eqn(self, eqn, name: str, path: str) -> None:
        if name == "dynamic_update_slice":
            operand, update = eqn.invars[0], eqn.invars[1]
            starts = list(eqn.invars[2:])
            widths = list(update.aval.shape) or [1] * len(starts)
            self._emit(eqn, name, path, write=True, batched=False,
                       operand=operand, starts=starts, widths=widths)
        elif name == "dynamic_slice":
            operand = eqn.invars[0]
            starts = list(eqn.invars[1:])
            widths = list(eqn.params.get("slice_sizes", ()))
            self._emit(eqn, name, path, write=False, batched=False,
                       operand=operand, starts=starts, widths=widths)
        elif name == "scatter":
            self._scatter_eqn(eqn, path)

    def _scatter_eqn(self, eqn, path: str) -> None:
        mode = str(eqn.params.get("mode", ""))
        if "CLIP" not in mode.upper():
            return  # FILL_OR_DROP drops OOB rows — a different contract
        operand, indices, updates = eqn.invars[:3]
        dn = eqn.params.get("dimension_numbers")
        if dn is None:
            return
        inserted = set(getattr(dn, "inserted_window_dims", ()) or ())
        obatch = set(getattr(dn, "operand_batching_dims", ()) or ())
        op_window = [d for d in range(len(operand.aval.shape))
                     if d not in inserted and d not in obatch]
        win = {od: int(updates.aval.shape[uw]) for od, uw in
               zip(op_window, getattr(dn, "update_window_dims", ()))}
        comps = self.parts.get(id(indices))
        starts: List[Any] = []
        widths: List[int] = []
        dims: List[int] = []
        for k, od in enumerate(
                getattr(dn, "scatter_dims_to_operand_dims", ())):
            starts.append(comps[k] if comps and k < len(comps) else None)
            widths.append(win.get(int(od), 1))
            dims.append(int(od))
        self._emit(eqn, "scatter", path, write=True,
                   batched=bool(obatch), operand=operand, starts=starts,
                   widths=widths, dims=dims)

    def _emit(self, eqn, op: str, path: str, *, write: bool,
              batched: bool, operand, starts, widths,
              dims: Optional[List[int]] = None) -> None:
        shape = list(operand.aval.shape)
        dims = dims if dims is not None else list(range(len(starts)))
        dd = False
        detail = ""
        safe = True
        for s, w, d in zip(starts, widths, dims):
            if s is None:
                safe, detail = False, f"dim {d}: untracked index"
                dd = True
                break
            lo, hi = self._aiv(s)
            dd = dd or self._adata(s)
            room = shape[d] - int(w)
            if lo < 0 or hi > room:
                safe = False
                span = (f"[{int(lo) if lo > NEG else '-inf'}, "
                        f"{int(hi) if hi < POS else 'inf'}]")
                detail = (f"dim {d}: start in {span}, room "
                          f"[0, {room}] for width {w} in {shape[d]}")
                break
        self.events.append(SliceEvent(
            op=op, path=path, source=_user_source(eqn.source_info),
            write=write, batched=batched, on_buffer=self._abuf(operand),
            data_dependent=dd, safe=safe, detail=detail))


def trace_slice_events(closed_jaxpr) -> List[SliceEvent]:
    """All dynamic-slice-family events in a traced program, with the
    interval-analysis safety verdict attached."""
    return _SliceWalker().walk_closed(closed_jaxpr)


__all__ = ["SliceEvent", "trace_slice_events"]

"""Structured findings: what the collective-consistency analyzer reports.

A :class:`Finding` is one rule violation, carrying the rule id, a
severity, the jaxpr path where the offending equation lives, and the
user-source provenance recovered from jax's ``source_info`` — enough
for a human to jump to the call site and for tools
(``scripts/lint_collectives.py --json``, ``plan_tool.py lint``) to
machine-process the report.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

# Severity ladder.  ERROR findings are correctness hazards (deadlocks,
# unbound axes, broken shard layouts) — the CLI exits nonzero on them
# and ``Config.analysis="error"`` raises.  WARNING findings are likely
# hazards or measurable performance losses; INFO findings are
# observations worth a look.
ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITY_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}


def severity_rank(severity: str) -> int:
    """Lower rank = more severe; unknown severities sort last."""
    return _SEVERITY_ORDER.get(severity, len(_SEVERITY_ORDER))


@dataclasses.dataclass
class Finding:
    """One rule violation.

    ``path`` is the jaxpr traversal path (e.g.
    ``pjit/shard_map/cond[1]``); ``source`` is the user frame recovered
    from the equation's ``source_info`` (``file.py:123 (fn)``), empty
    when jax did not record one.
    """

    rule: str
    severity: str
    message: str
    path: str = ""
    source: str = ""
    op: str = ""
    axes: Tuple[str, ...] = ()
    nbytes: int = 0

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["axes"] = list(self.axes)
        return d

    @staticmethod
    def from_json(d: dict) -> "Finding":
        fields = {f.name for f in dataclasses.fields(Finding)}
        kept = {k: v for k, v in d.items() if k in fields}
        kept["axes"] = tuple(kept.get("axes") or ())
        return Finding(**kept)

    def __str__(self) -> str:
        loc = self.source or self.path or "<unknown>"
        extra = ""
        if self.op:
            extra = f" [{self.op}"
            if self.axes:
                extra += f" over {'x'.join(self.axes)}"
            extra += "]"
        return f"{self.rule} {self.severity}: {self.message}{extra} at {loc}"


def sort_findings(findings: Sequence[Finding]) -> List[Finding]:
    """Severity-major, then rule id — the report order every surface
    (API return value, CLI text, ``--json``) shares."""
    return sorted(findings,
                  key=lambda f: (severity_rank(f.severity), f.rule, f.path))


def max_severity(findings: Sequence[Finding]) -> Optional[str]:
    """The most severe level present, or None for a clean bill."""
    if not findings:
        return None
    return min((f.severity for f in findings), key=severity_rank)


def has_errors(findings: Sequence[Finding]) -> bool:
    return any(f.severity == ERROR for f in findings)


def format_findings(findings: Sequence[Finding]) -> str:
    if not findings:
        return "clean: no findings"
    lines = [str(f) for f in sort_findings(findings)]
    return "\n".join(lines)

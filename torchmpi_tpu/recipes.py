"""Reusable training-step recipes.

TorchMPI was "a communication library plus two thin integration layers", not
a trainer (SURVEY.md §1) — this module keeps that boundary: it contains no
training loop, just the canonical composition of the library's own pieces
(``nn.synchronize_gradients`` + BatchNorm-stats sync + metric reduction
inside a ``data_parallel_step``), so the examples, benchmark, and driver
entry points share one definition of the data-parallel step instead of four
copies.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import optax

from . import collectives, nn, runtime
from .parallel import gradsync as _gradsync
from .parallel import zero as parallel_zero


def make_bn_dp_train_step(
    model: Any,
    tx: optax.GradientTransformation,
    *,
    mesh=None,
    backend: Optional[str] = None,
    n_buckets: Optional[int] = None,
    donate: bool = True,
    remat: bool = False,
    zero: int = 0,
    params_template: Any = None,
    overlap: Optional[str] = None,
) -> Callable:
    """Build the canonical data-parallel SGD step for a flax model carrying a
    ``batch_stats`` (BatchNorm) collection.

    Returned callable: ``dp_step(params, opt_state, batch_stats, images,
    labels) -> (params, opt_state, batch_stats, loss)`` — gradients
    allreduced through the selector-routed backend, BatchNorm running stats
    cross-replica averaged on the same path, loss reduced for logging.

    ``zero=1`` (or ``True``) switches gradient sync + update to ZeRO-1
    (:mod:`torchmpi_tpu.parallel.zero`): reduce_scatter / shard-local
    optimizer / all_gather, with the optimizer state physically sharded
    over the mesh — numerically identical, 1/n the optimizer memory.
    Build ``opt_state`` with ``zero.init(params, tx, mesh=mesh)`` (not
    ``tx.init``); ``n_buckets`` does not apply (the reduce_scatter is one
    fused collective); ``Config(gradsync_compress="bf16")`` is honored on
    the gradient reduce_scatter exactly like the replicated path.

    ``overlap`` (default: ``config.gradsync_overlap``) switches the
    gradient computation to the backprop-overlapped schedule
    (``gradsync.make_overlapped_grad_fn`` — docs/OVERLAP.md): each
    reverse-parameter-order bucket's allreduce fires inside the
    backward pass as its cotangents materialize, bit-identical
    gradients to the post-backward path.  With ``zero=1``/``zero=3``
    the overlapped (already-reduced) gradients reach the optimizer
    through a local shard slice (``zero.update(presynced=True)``)
    instead of a second reduce_scatter.  ``"off"`` (the default
    default) leaves the dispatch byte-for-byte as before.

    ``zero=3`` additionally stores the PARAMETERS sharded between steps:
    the step's ``params`` argument is the flat shard from
    ``zero.shard_params(params, mesh=mesh)``, all-gathered transiently at
    the top of each step and never re-materialized after the update —
    persistent params + optimizer memory both drop to 1/n.  Export full
    params with ``zero.unshard_params``.  ``batch_stats`` stays replicated
    (it is updated by a cross-replica mean, not by ``tx``).
    """
    zero = int(zero)
    if zero not in (0, 1, 3):
        raise ValueError(f"zero must be 0, 1, or 3, got {zero}")
    m = mesh if mesh is not None else runtime.current_mesh()
    axes = tuple(m.axis_names)
    if overlap is None:
        cfg0 = runtime.config() if runtime.is_initialized() else None
        overlap = cfg0.gradsync_overlap if cfg0 is not None else "off"
    if overlap not in ("off", "auto"):
        raise ValueError(f"overlap must be off|auto, got {overlap!r}")
    overlap_on = overlap == "auto"
    spec3 = None
    if zero == 3:
        if params_template is None:
            raise ValueError(
                "zero=3 stores params as a flat shard; pass params_template"
                " (the full parameter pytree, or its eval_shape) so the step"
                " can map shards back to the model structure")
        spec3 = parallel_zero.flat_spec(params_template, axes, mesh=m)

    def forward(variables, images):
        return model.apply(variables, images, train=True,
                           mutable=["batch_stats"])

    if remat:
        # Rematerialize the forward in backward: trades FLOPs for HBM — the
        # standard lever when activations, not params, bound the per-chip
        # batch (SURVEY blueprint's HBM note).
        forward = jax.checkpoint(forward)

    def step(params, opt_state, batch_stats, images, labels):
        # zero=3: ``params`` is the flat shard; materialize the full tree
        # only for the duration of this step.
        full = (parallel_zero.gather_params(params, spec3, axes,
                                            backend=backend)
                if zero == 3 else params)

        def loss_fn(p):
            logits, updated = forward(
                {"params": p, "batch_stats": batch_stats}, images)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
            return loss, updated["batch_stats"]

        if overlap_on:
            # Backprop-overlapped schedule: the bucketed allreduces
            # fire inside this value_and_grad's backward pass, so the
            # grads come back already reduced (docs/OVERLAP.md).
            (loss, new_stats), grads = _gradsync.make_overlapped_grad_fn(
                loss_fn, full, axes, mesh=m, backend=backend,
                has_aux=True)(full)
        else:
            (loss, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(full)
        if zero == 3:
            params, opt_state = parallel_zero.update3(
                params, grads, opt_state, tx, axes, spec=spec3,
                backend=backend, presynced=overlap_on)
        elif zero == 1:
            params, opt_state = parallel_zero.update(
                full, grads, opt_state, tx, axes, backend=backend,
                presynced=overlap_on)
        else:
            if not overlap_on:
                grads = nn.synchronize_gradients(grads, axes,
                                                 backend=backend,
                                                 n_buckets=n_buckets)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        new_stats = collectives.allreduce_in_axis(new_stats, axes, op="mean",
                                                  backend=backend)
        loss = collectives.allreduce_in_axis(loss, axes, op="mean")
        return (params, opt_state, new_stats, loss)

    if not zero:
        return nn.data_parallel_step(
            step, mesh=m, batch_argnums=(3, 4),
            donate_argnums=(0, 1, 2) if donate else ())

    # ZeRO path: the optimizer state (and for zero=3 the flat param shard)
    # crosses the shard_map boundary SHARDED (P(axes) on per-parameter
    # leaves), so the generic replicated-state wrapper does not apply —
    # build the specs from the state's own pytree.
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    batch_spec = P(axes)
    param_spec = P(axes) if zero == 3 else P()

    def wrapped(params, opt_state, batch_stats, images, labels):
        sspecs = parallel_zero.specs_like(opt_state, axes)
        fn = shard_map(
            step, mesh=m,
            in_specs=(param_spec, sspecs, P(), batch_spec, batch_spec),
            out_specs=(param_spec, sspecs, P(), P()), check_vma=False)
        out = fn(params, opt_state, batch_stats, images, labels)
        return out, _gradsync.completion_token(out)

    jitted = jax.jit(wrapped,
                     donate_argnums=(0, 1, 2) if donate else ())
    cfg = runtime.config() if runtime.is_initialized() else None
    mode = getattr(cfg, "analysis", "off") if cfg is not None else "off"
    if mode in ("warn", "error"):
        from . import analysis

        jitted = analysis.wrap_step(
            jitted, wrapped, label=f"bn_dp_train_step(zero={zero})",
            mode=mode)
    if cfg is not None and cfg.obs != "off":
        from . import obs

        obs.record_step_build(f"bn_dp_train_step(zero={zero})")
    return _gradsync.throttle_dispatch(jitted, mesh=m)


def fsdp_specs(params: Any, axis_names=None, *, mesh=None) -> Any:
    """Per-leaf ``PartitionSpec`` for annotation-driven FSDP: shard each
    parameter's largest ``n``-divisible dimension over the DP axes,
    replicate leaves that have none (tiny biases).  The ONE definition of
    the FSDP layout, shared by :func:`make_fsdp_train_step` and tests."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    m = mesh if mesh is not None else runtime.current_mesh()
    axes = tuple(m.axis_names) if axis_names is None else (
        (axis_names,) if isinstance(axis_names, str) else tuple(axis_names))
    n = int(np.prod([m.shape[a] for a in axes]))
    entry = axes if len(axes) > 1 else axes[0]

    def leaf_spec(leaf):
        shape = getattr(leaf, "shape", ())
        for i in sorted(range(len(shape)), key=lambda i: -shape[i]):
            if shape[i] >= n and shape[i] % n == 0:
                spec = [None] * len(shape)
                spec[i] = entry
                return P(*spec)
        return P()

    return jax.tree.map(leaf_spec, params)


def make_fsdp_train_step(model, tx: optax.GradientTransformation,
                         params: Any, *, mesh=None, remat: bool = False,
                         donate: bool = True,
                         loss_fn: Optional[Callable] = None
                         ) -> Tuple[Callable, Any, Any]:
    """Annotation-driven FSDP (the GSPMD / scaling-book recipe), the
    idiomatic-TPU complement to the explicit flat ZeRO-3 of
    ``make_bn_dp_train_step(zero=3)``: parameters and optimizer state LIVE
    sharded per-parameter (:func:`fsdp_specs`), the train step is plain
    single-program code under ``jit``, and XLA's sharding propagation
    inserts the per-use parameter all-gathers and gradient reduce-scatters
    itself — which lets the compiler schedule gathers layer-by-layer, a
    memory profile the hand-written whole-model flat gather cannot express.

    ``model`` is a plain (BatchNorm-free) module.  By default it is
    treated as a classifier (``apply({"params"}, x) -> logits`` against
    integer labels); pass ``loss_fn(apply_fn, params, xb, yb) -> scalar``
    for any other objective — e.g. a next-token LM loss — where
    ``apply_fn`` is the (possibly rematerialized) ``model.apply``.
    Returns ``(step, params, opt_state)`` with the state already placed
    sharded; ``step(params, opt_state, xb, yb) -> (params, opt_state,
    loss)``.  Place batches with ``P(axes)`` on the leading dim
    (``prefetch_to_mesh`` or ``device_put``).  Numerics equal full-batch
    single-device SGD (test_zero.py proves it).
    """
    from jax.sharding import NamedSharding

    m = mesh if mesh is not None else runtime.current_mesh()
    specs = fsdp_specs(params, mesh=m)
    shardings = jax.tree.map(lambda s: NamedSharding(m, s), specs)
    params = jax.device_put(params, shardings)  # one batched transfer
    # Explicit out_shardings: momenta are built by zeros_like (constants, no
    # data edge from the sharded params), so propagation alone would land
    # the whole state tree on one device at init.  The same per-leaf rule
    # as the params gives param-shaped state leaves the matching layout and
    # scalars (step counts) replication — and keeps the step's input
    # shardings stable from the first call (no silent recompile).
    state_shapes = jax.eval_shape(tx.init, params)
    state_shardings = jax.tree.map(
        lambda s: NamedSharding(m, s), fsdp_specs(state_shapes, mesh=m))
    opt_state = jax.jit(tx.init, out_shardings=state_shardings)(params)

    forward = model.apply
    if remat:
        forward = jax.checkpoint(forward)

    if loss_fn is None:
        def loss_fn(apply_fn, p, images, labels):
            logits = apply_fn({"params": p}, images)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()

    def step(params, opt_state, xb, yb):
        def objective(p):
            return loss_fn(forward, p, xb, yb)

        loss, grads = jax.value_and_grad(objective)(params)
        updates, opt_state_ = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        # Pin both outputs to the FSDP layout: XLA then solves the backward
        # for a reduce-scatter of each grad instead of a full all-reduce,
        # and the state output keeps the donated input's layout (otherwise
        # propagation could re-replicate it, losing both the aliasing and
        # the 1/n persistent memory).
        new_params = jax.lax.with_sharding_constraint(new_params, shardings)
        opt_state_ = jax.lax.with_sharding_constraint(opt_state_,
                                                      state_shardings)
        return new_params, opt_state_, loss

    step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return step, params, opt_state


def replicate_bn_state(params, opt_state, batch_stats, *, mesh=None
                       ) -> Tuple[Any, Any, Any]:
    """Replicate (params, opt_state, batch_stats) across the mesh — the
    synchronizeParameters step of the recipe."""
    return (nn.synchronize_parameters(params, mesh=mesh),
            nn.synchronize_parameters(opt_state, mesh=mesh),
            nn.synchronize_parameters(batch_stats, mesh=mesh))

"""Reusable training-step recipes.

TorchMPI was "a communication library plus two thin integration layers", not
a trainer (SURVEY.md §1) — this module keeps that boundary: it contains no
training loop, just the canonical composition of the library's own pieces
(``nn.synchronize_gradients`` + BatchNorm-stats sync + metric reduction
inside a ``data_parallel_step``), so the examples, benchmark, and driver
entry points share one definition of the data-parallel step instead of four
copies.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import optax

from . import collectives, nn, runtime
from .parallel import gradsync as _gradsync
from .parallel import zero as parallel_zero


def make_bn_dp_train_step(
    model: Any,
    tx: optax.GradientTransformation,
    *,
    mesh=None,
    backend: Optional[str] = None,
    n_buckets: Optional[int] = None,
    donate: bool = True,
    remat: bool = False,
    zero: bool = False,
) -> Callable:
    """Build the canonical data-parallel SGD step for a flax model carrying a
    ``batch_stats`` (BatchNorm) collection.

    Returned callable: ``dp_step(params, opt_state, batch_stats, images,
    labels) -> (params, opt_state, batch_stats, loss)`` — gradients
    allreduced through the selector-routed backend, BatchNorm running stats
    cross-replica averaged on the same path, loss reduced for logging.

    ``zero=True`` switches gradient sync + update to ZeRO-1
    (:mod:`torchmpi_tpu.parallel.zero`): reduce_scatter / shard-local
    optimizer / all_gather, with the optimizer state physically sharded
    over the mesh — numerically identical, 1/n the optimizer memory.
    Build ``opt_state`` with ``zero.init(params, tx, mesh=mesh)`` (not
    ``tx.init``); ``n_buckets`` does not apply (the reduce_scatter is one
    fused collective); ``Config(gradsync_compress="bf16")`` is honored on
    the gradient reduce_scatter exactly like the replicated path.
    """
    m = mesh if mesh is not None else runtime.current_mesh()
    axes = tuple(m.axis_names)

    def forward(variables, images):
        return model.apply(variables, images, train=True,
                           mutable=["batch_stats"])

    if remat:
        # Rematerialize the forward in backward: trades FLOPs for HBM — the
        # standard lever when activations, not params, bound the per-chip
        # batch (SURVEY blueprint's HBM note).
        forward = jax.checkpoint(forward)

    def step(params, opt_state, batch_stats, images, labels):
        def loss_fn(p):
            logits, updated = forward(
                {"params": p, "batch_stats": batch_stats}, images)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
            return loss, updated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        if zero:
            params, opt_state = parallel_zero.update(
                params, grads, opt_state, tx, axes, backend=backend)
        else:
            grads = nn.synchronize_gradients(grads, axes, backend=backend,
                                             n_buckets=n_buckets)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        new_stats = collectives.allreduce_in_axis(new_stats, axes, op="mean",
                                                  backend=backend)
        loss = collectives.allreduce_in_axis(loss, axes, op="mean")
        return (params, opt_state, new_stats, loss)

    if not zero:
        return nn.data_parallel_step(
            step, mesh=m, batch_argnums=(3, 4),
            donate_argnums=(0, 1, 2) if donate else ())

    # ZeRO path: the optimizer state crosses the shard_map boundary SHARDED
    # (P(axes) on per-parameter leaves), so the generic replicated-state
    # wrapper does not apply — build the specs from the state's own pytree.
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    batch_spec = P(axes)

    def wrapped(params, opt_state, batch_stats, images, labels):
        sspecs = parallel_zero.specs_like(opt_state, axes)
        fn = shard_map(
            step, mesh=m,
            in_specs=(P(), sspecs, P(), batch_spec, batch_spec),
            out_specs=(P(), sspecs, P(), P()), check_vma=False)
        out = fn(params, opt_state, batch_stats, images, labels)
        return out, _gradsync.completion_token(out)

    jitted = jax.jit(wrapped,
                     donate_argnums=(0, 1, 2) if donate else ())
    return _gradsync.throttle_dispatch(jitted, mesh=m)


def replicate_bn_state(params, opt_state, batch_stats, *, mesh=None
                       ) -> Tuple[Any, Any, Any]:
    """Replicate (params, opt_state, batch_stats) across the mesh — the
    synchronizeParameters step of the recipe."""
    return (nn.synchronize_parameters(params, mesh=mesh),
            nn.synchronize_parameters(opt_state, mesh=mesh),
            nn.synchronize_parameters(batch_stats, mesh=mesh))

"""Collective implementation selector.

Rebuild of the reference's ``mpi.collectiveSelector`` (SURVEY.md §3 C9,
reconstructed — reference mount empty): a runtime-switchable table that picked
an implementation per (cpu|gpu) x (singlenode|multinode) among
{mpi, nccl, gloo, p2p/custom}.  On TPU the discriminators become the mesh
topology and tensor size, and the implementations become:

- ``"xla"``          stock XLA collectives over the whole mesh (the mpi/nccl
                     analog; XLA's allreduce is the tuned vendor path).
- ``"hierarchical"`` explicit two-level staging: reduce_scatter over ICI ->
                     allreduce over DCN -> all_gather over ICI (the analog of
                     the reference's custom hierarchical intra-node reduce ->
                     inter-node allreduce -> intra-node broadcast).
- ``"pallas"``       hand-written chunked ring kernels over ICI remote DMA
                     (the analog of the reference's custom chunked/pipelined
                     MPI_Isend/Irecv rings).

Backends self-register; lookup is by name with size-cutover logic mirroring the
reference's "small tensors stay on the stock path" constants.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

# op name -> backend name -> implementation fn.  Implementation signature is
# op-specific; see collectives.py _IN_AXIS_OPS.
_REGISTRY: Dict[str, Dict[str, Callable]] = {}


def register(op: str, backend: str, fn: Callable) -> None:
    _REGISTRY.setdefault(op, {})[backend] = fn


def available(op: Optional[str] = None) -> Dict:
    """Introspection (reference: ``mpi.collectiveAvailability``)."""
    if op is not None:
        return dict(_REGISTRY.get(op, {}))
    return {k: sorted(v.keys()) for k, v in _REGISTRY.items()}


def select(
    op: str,
    backend: str,
    *,
    nbytes: Optional[int] = None,
    custom_min_bytes: int = 0,
    n_dcn: int = 1,
    explicit: bool = False,
) -> Callable:
    """Pick the implementation for ``op``.

    Falls back to ``"xla"`` when the requested backend has no implementation
    for this op, when the tensor is below the custom-path size cutover, or
    when a hierarchical backend is requested on a flat (n_dcn == 1) mesh —
    the same graceful degradation the reference's selector performed when
    NCCL/Gloo were compiled out.  ``explicit=True`` (a per-call backend
    request, as opposed to the config default) bypasses the size cutover but
    still degrades on topology/availability.
    """
    impls = _REGISTRY.get(op)
    if not impls:
        raise KeyError(f"no implementations registered for collective {op!r}")
    name = backend
    if name != "xla":
        if (not explicit and nbytes is not None
                and nbytes < custom_min_bytes):
            name = "xla"
        elif name == "hierarchical" and n_dcn <= 1:
            name = "xla"
        elif name not in impls:
            name = "xla"
    if name not in impls:
        raise KeyError(
            f"collective {op!r} has no {name!r} implementation "
            f"(available: {sorted(impls)})"
        )
    return impls[name]


def nbytes_of(x) -> int:
    return int(np.prod(x.shape)) * x.dtype.itemsize if hasattr(x, "shape") else 0

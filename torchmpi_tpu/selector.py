"""Collective implementation selector.

Rebuild of the reference's ``mpi.collectiveSelector`` (SURVEY.md §3 C9,
reconstructed — reference mount empty): a runtime-switchable table that picked
an implementation per (cpu|gpu) x (singlenode|multinode) among
{mpi, nccl, gloo, p2p/custom}.  On TPU the discriminators become the mesh
topology and tensor size, and the implementations become:

- ``"xla"``          stock XLA collectives over the whole mesh (the mpi/nccl
                     analog; XLA's allreduce is the tuned vendor path).
- ``"hierarchical"`` explicit two-level staging: reduce_scatter over ICI ->
                     allreduce over DCN -> all_gather over ICI (the analog of
                     the reference's custom hierarchical intra-node reduce ->
                     inter-node allreduce -> intra-node broadcast).
- ``"pallas"``       hand-written chunked ring kernels over ICI remote DMA
                     (the analog of the reference's custom chunked/pipelined
                     MPI_Isend/Irecv rings).

Backends self-register; lookup is by name with size-cutover logic mirroring the
reference's "small tensors stay on the stock path" constants.

``nbytes`` is the real transfer size: the fused pytree collectives
(torchmpi_tpu/fusion.py) coalesce a tree's leaves into dtype-grouped
buckets BEFORE routing, so the cutover and the tuning-plan provider see
the fused bucket's bytes — not per-leaf crumbs that would always fall
below ``custom_min_bytes`` and key plan entries at sizes nobody measured.
"""

from __future__ import annotations

import warnings
from typing import Callable, Dict, Optional, Tuple

import numpy as np

# op name -> backend name -> implementation fn.  Implementation signature is
# op-specific; see collectives.py _IN_AXIS_OPS.
_REGISTRY: Dict[str, Dict[str, Callable]] = {}

# Bumped by every register(): part of each CollectivePlan key
# (torchmpi_tpu/planner.py), so re-registering an implementation at
# runtime strands the plans that resolved the old one — the planner's
# analog of the legacy jit-cache keying on the resolved impl object.
_generation = 0


def generation() -> int:
    return _generation


def register(op: str, backend: str, fn: Callable) -> None:
    global _generation
    _REGISTRY.setdefault(op, {})[backend] = fn
    _generation += 1


def available(op: Optional[str] = None) -> Dict:
    """Introspection (reference: ``mpi.collectiveAvailability``)."""
    if op is not None:
        return dict(_REGISTRY.get(op, {}))
    return {k: sorted(v.keys()) for k, v in _REGISTRY.items()}


# Plan provider hook (torchmpi_tpu.tuning): fn(op, nbytes, dtype, axes)
# -> Optional[backend name].  Registered by tuning.configure() when the
# config opts into backend="auto"; consulted by select() BEFORE the
# static cutover so measured per-(op, size, mesh) decisions take
# precedence over the hand-tuned constants.
_plan_provider: Optional[Callable] = None


def set_plan_provider(fn: Callable) -> None:
    global _plan_provider
    _plan_provider = fn


def clear_plan_provider() -> None:
    global _plan_provider
    _plan_provider = None


def plan_provider() -> Optional[Callable]:
    return _plan_provider


def select(
    op: str,
    backend: str,
    *,
    nbytes: Optional[int] = None,
    custom_min_bytes: int = 0,
    n_dcn: int = 1,
    explicit: bool = False,
    dtype=None,
    axes=None,
) -> Callable:
    """Pick the implementation for ``op``.

    ``backend="auto"`` consults the registered tuning-plan provider (a
    measured, persisted per-topology decision — see
    ``torchmpi_tpu/tuning/``) BEFORE the static cutover; a plan hit
    bypasses the ``custom_min_bytes`` heuristic (the entry was measured
    at this size bucket), a miss degrades to the stock ``"xla"`` path.

    Falls back to ``"xla"`` when the requested backend has no implementation
    for this op, when the tensor is below the custom-path size cutover, or
    when a hierarchical backend is requested on a flat (n_dcn == 1) mesh —
    the same graceful degradation the reference's selector performed when
    NCCL/Gloo were compiled out.  ``explicit=True`` (a per-call backend
    request, as opposed to the config default) bypasses the size cutover but
    still degrades on topology/availability.
    """
    impls = _REGISTRY.get(op)
    if not impls:
        raise KeyError(f"no implementations registered for collective {op!r}")
    name = backend
    if name == "auto":
        planned = None
        if _plan_provider is not None:
            try:
                planned = _plan_provider(op, int(nbytes or 0), dtype, axes)
            except Exception:  # noqa: BLE001 — a plan must never crash a step
                planned = None
        if planned is None:
            name = "xla"
        else:
            # A measured plan decision carries the same authority as an
            # explicit per-call backend: no size cutover, but topology/
            # availability degradation below still applies.
            name = planned
            explicit = True
    if name != "xla":
        if (not explicit and nbytes is not None
                and nbytes < custom_min_bytes):
            name = "xla"
        elif name == "hierarchical" and n_dcn <= 1:
            # Topology degradation must be VISIBLE: a requested
            # two-level backend silently running flat is exactly the
            # misconfiguration (wrong dcn_size, collapsed mesh) that
            # otherwise only shows up as a missing perf win.
            _note_fallback(op, name, "flat mesh (n_dcn <= 1)")
            name = "xla"
        elif name not in impls:
            name = "xla"
    if name not in impls:
        raise KeyError(
            f"collective {op!r} has no {name!r} implementation "
            f"(available: {sorted(impls)})"
        )
    return impls[name]


# (op, backend) pairs already warned about this process: the warning is
# one-time per pair (a hot loop degrading every dispatch must not spam),
# while the obs counter counts every degradation.
_warned_fallbacks: set = set()


def _note_fallback(op: str, backend: str, reason: str, *,
                   target: str = "'xla'") -> None:
    """Surface a topology/availability degradation: a one-time
    ``RuntimeWarning`` per (op, backend) plus the
    ``tm_selector_fallback_total`` counter when obs is on — so
    ``obs_tool`` dumps show a requested "hierarchical" that silently
    ran flat (ISSUE 8 satellite; docs/HIERARCHICAL.md).  ``target``
    names what actually ran: :func:`select` degrades to the stock
    'xla' impl, while the error-feedback flat-span callers degrade to
    the plain uncompressed sync path (which routes through the
    selector as usual)."""
    key: Tuple[str, str] = (op, backend)
    if key not in _warned_fallbacks:
        _warned_fallbacks.add(key)
        warnings.warn(
            f"collective {op!r}: {backend!r} requested but degraded "
            f"to {target} ({reason}); check dcn_size/mesh_shape "
            f"if a two-level topology was intended",
            RuntimeWarning, stacklevel=4)
    from . import runtime

    if runtime.effective_config().obs != "off":
        from . import obs

        obs.record_selector_fallback(op, backend)


def name_of(op: str, impl: Callable) -> str:
    """Reverse lookup: the backend name a resolved implementation was
    registered under (telemetry labels — ``torchmpi_tpu.obs``).
    Implementations not in the registry report ``"custom"``."""
    for b, f in _REGISTRY.get(op, {}).items():
        if f is impl:
            return b
    return "custom"


def nbytes_of(x) -> int:
    """Total payload bytes of ``x`` — a single array OR any pytree of
    arrays, summed across leaves, so gradient-tree callers get real
    sizes for cutover/bucketing decisions.  Leaves without shape/dtype
    (python scalars, None) contribute 0, preserving the old behavior of
    returning 0 for non-arrays."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
    import jax

    total = 0
    for leaf in jax.tree.leaves(x):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            total += int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
    return total

"""Metrics registry: counters + log2-bucketed histograms.

The in-memory store behind ``torchmpi_tpu.obs`` (docs/OBSERVABILITY.md).
Deliberately dependency-free (no jax, no numpy): the registry must be
importable by the dump path of a dying process (SIGTERM handler,
interpreter teardown) and by ``scripts/obs_tool.py`` without paying a
jax import.

Metrics are keyed by ``(name, labels)`` where labels is a small dict of
string pairs — the Prometheus data model, which is also what the JSONL
exposition serializes.  Histograms bucket observed values at
``floor(log2(v))`` — the same granularity as the tuning-plan size
buckets (``tuning/fingerprint.size_bucket``): collective byte sizes and
latencies move in powers of two, and a handful of buckets covers a
training run.

Thread safety: one lock around every mutation.  The hot call sites
(eager collective dispatch) take it once per collective launch — noise
next to the dispatch itself, and only ever paid when ``Config.obs`` is
on.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def log2_bucket(value: float) -> int:
    """``floor(log2(value))``; values <= 1 share bucket 0 (mirrors
    ``tuning.fingerprint.size_bucket`` so byte histograms and plan keys
    bucket identically)."""
    return max(0, int(value).bit_length() - 1)


class _Hist:
    __slots__ = ("buckets", "count", "sum")

    def __init__(self) -> None:
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        b = log2_bucket(value)
        self.buckets[b] = self.buckets.get(b, 0) + 1
        self.count += 1
        self.sum += float(value)


class Registry:
    """Counter + histogram store with JSONL/Prometheus exposition."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, LabelKey], float] = {}
        self._hists: Dict[Tuple[str, LabelKey], _Hist] = {}

    # -- mutation ----------------------------------------------------------

    def counter_inc(self, name: str, value: float = 1, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    def hist_observe(self, name: str, value: float, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Hist()
            h.observe(value)

    def counter_handle(self, name: str, **labels):
        """Pre-resolved increment handle for ONE counter series: the
        (name, labels) key is built once, so the per-event cost is a
        lock + dict update.  The planner's replay-path discipline —
        every label a CollectivePlan emits is static per plan, so the
        key resolution moves to plan-build time."""
        key = (name, _label_key(labels))
        lock, counters = self._lock, self._counters

        def inc(value: float = 1) -> None:
            with lock:
                counters[key] = counters.get(key, 0) + value

        return inc

    def hist_handle(self, name: str, **labels):
        """Pre-resolved observe handle for ONE histogram series (the
        histogram sibling of :meth:`counter_handle`)."""
        key = (name, _label_key(labels))
        lock, hists = self._lock, self._hists

        def observe(value: float) -> None:
            with lock:
                h = hists.get(key)
                if h is None:
                    h = hists[key] = _Hist()
                h.observe(value)

        return observe

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._hists.clear()

    # -- reads -------------------------------------------------------------

    def counter(self, name: str, **labels) -> float:
        """Current value of one counter series (0 if never incremented)."""
        return self._counters.get((name, _label_key(labels)), 0)

    def counter_total(self, name: str) -> float:
        """Sum of a counter across every label combination."""
        with self._lock:
            return sum(v for (n, _), v in self._counters.items()
                       if n == name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted({n for n, _ in self._counters}
                          | {n for n, _ in self._hists})

    def snapshot(self, best_effort: bool = False) -> List[dict]:
        """Every series as a JSON-ready record (the JSONL dump body and
        the obs_tool interchange format).

        ``best_effort=True`` is for the SIGTERM dump path: the signal
        handler runs on the main thread, and if the interrupted frame
        holds this (non-reentrant) lock a blocking acquire would
        self-deadlock the very dump the handler exists to produce.  The
        acquire is bounded; on timeout the copy proceeds lock-free —
        safe in the deadlock case (the holder is the suspended frame,
        so every other writer is blocked on the same lock)."""
        got = self._lock.acquire(timeout=0.2 if best_effort else -1)
        try:
            out: List[dict] = []
            for (name, lk), v in sorted(self._counters.items()):
                out.append({"kind": "counter", "name": name,
                            "labels": dict(lk), "value": v})
            for (name, lk), h in sorted(self._hists.items()):
                out.append({"kind": "hist", "name": name,
                            "labels": dict(lk),
                            "buckets": {str(b): c for b, c
                                        in sorted(h.buckets.items())},
                            "count": h.count, "sum": h.sum})
            return out
        finally:
            if got:
                self._lock.release()

    # -- Prometheus text exposition ---------------------------------------

    def to_prometheus(self, snapshot: Optional[List[dict]] = None) -> str:
        """Prometheus text format (0.0.4).  Histograms render as
        cumulative ``_bucket{le=2^(b+1)}`` series plus ``_count``/
        ``_sum`` — the upper edge of log2 bucket b is ``2**(b+1)``."""
        return "\n".join(prometheus_lines(
            self.snapshot() if snapshot is None else snapshot)) + "\n"


def prometheus_lines(records: List[dict]) -> Iterator[str]:
    """Render snapshot records (``Registry.snapshot`` shape) as
    Prometheus text lines — module-level so obs_tool can render files
    it parsed back from JSONL without a live Registry."""
    seen_type = set()
    for rec in records:
        name, labels = rec.get("name"), rec.get("labels", {})
        if rec.get("kind") == "counter":
            if name not in seen_type:
                seen_type.add(name)
                yield f"# TYPE {name} counter"
            yield f"{name}{_prom_labels(labels)} {_prom_num(rec['value'])}"
        elif rec.get("kind") == "hist":
            if name not in seen_type:
                seen_type.add(name)
                yield f"# TYPE {name} histogram"
            acc = 0
            for b, c in sorted(rec.get("buckets", {}).items(),
                               key=lambda kv: int(kv[0])):
                acc += c
                le = dict(labels, le=str(2 ** (int(b) + 1)))
                yield f"{name}_bucket{_prom_labels(le)} {acc}"
            inf = dict(labels, le="+Inf")
            yield f"{name}_bucket{_prom_labels(inf)} {rec['count']}"
            yield f"{name}_count{_prom_labels(labels)} {rec['count']}"
            yield f"{name}_sum{_prom_labels(labels)} {_prom_num(rec['sum'])}"


def _esc(v: object) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _prom_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(labels.items()))
    return "{" + body + "}"


def _prom_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)

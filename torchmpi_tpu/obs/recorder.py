"""Deadlock flight recorder: a fixed-size ring of recent collective events.

The runtime complement to the static analyzer's D1/D3 deadlock rules
(docs/ANALYSIS.md): when a pod hangs, each host's last N collective
launches — op, payload bytes, backend, a per-host sequence number, and a
monotonic timestamp — are the evidence.  Events are appended *before*
dispatch, so the collective a host is stuck inside is the last event in
its ring.  The dump is per-host JSONL; ``scripts/obs_tool.py blame``
aligns the per-host seq streams and names the first diverging collective
(different op/bytes at the same seq, or one host issuing launches the
others never reached — the SPMD divergence that deadlocks a gang).

Dependency-free (no jax/numpy) and allocation-light: one preallocated
list reused circularly, one lock, tuples for events.  Only ever touched
when ``Config.obs`` is on.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

# Event tuple layout (kept positional to stay allocation-light on the
# dispatch path; to_records() names the fields for the dump).  The
# event-type field is "ev", NOT "kind" — "kind" is the JSONL framing
# discriminator (meta/counter/hist/event) in the dump files.
# (seq, ts_monotonic, ev, op, nbytes, backend, detail)
Event = Tuple[int, float, str, str, int, str, str]

FIELDS = ("seq", "ts", "ev", "op", "nbytes", "backend", "detail")


class FlightRecorder:
    """Fixed-size in-memory ring of the last N events."""

    def __init__(self, size: int = 1024) -> None:
        self.size = max(1, int(size))
        self._lock = threading.Lock()
        self._ring: List[Optional[Event]] = [None] * self.size
        self._seq = 0  # total events ever appended
        # Lowest retained seq.  Normally implied by seq - size, but a
        # grow via resized() carries fewer than ``size`` events, so the
        # floor is tracked explicitly until appends overwrite past it.
        self._lo = 0

    def append(self, ev: str, op: str = "", nbytes: int = 0,
               backend: str = "", detail: str = "") -> int:
        """Record one event; returns its sequence number."""
        ts = time.monotonic()
        with self._lock:
            seq = self._seq
            self._ring[seq % self.size] = (seq, ts, ev, op, int(nbytes),
                                           backend, detail)
            self._seq = seq + 1
        return seq

    def _start(self) -> int:
        """Seq of the oldest retained event."""
        return max(self._lo, self._seq - self.size)

    def __len__(self) -> int:
        return self._seq - self._start()

    @property
    def total(self) -> int:
        """Events ever appended (>= len once the ring has wrapped)."""
        return self._seq

    @property
    def dropped(self) -> int:
        """Events no longer retained (overwritten or lost to a
        shrink)."""
        return self._start()

    def events(self, best_effort: bool = False) -> List[Event]:
        """Retained events, oldest first, seq-contiguous.

        ``best_effort=True``: bounded lock acquire with a lock-free
        fallback — the SIGTERM dump path, where a blocking acquire
        against the interrupted frame's own lock would self-deadlock
        (see ``Registry.snapshot``)."""
        got = self._lock.acquire(timeout=0.2 if best_effort else -1)
        try:
            return [self._ring[i % self.size]
                    for i in range(self._start(), self._seq)]
        finally:
            if got:
                self._lock.release()

    def to_records(self, best_effort: bool = False) -> List[dict]:
        """JSON-ready event records for the per-host dump (framed with
        ``kind="event"`` for the JSONL record discriminator)."""
        return [dict(zip(FIELDS, e), kind="event")
                for e in self.events(best_effort)]

    def resized(self, size: int) -> "FlightRecorder":
        """A new ring of ``size`` carrying this one's event history and
        sequence counter forward (the newest ``size`` events survive) —
        re-activation with a different ``obs_ring_size`` must not
        destroy the deadlock evidence the ring exists to retain."""
        nr = FlightRecorder(size)
        evs = self.events()  # takes the lock itself (non-reentrant)
        nr._seq = evs[-1][0] + 1 if evs else 0
        kept = evs[-nr.size:]
        nr._lo = kept[0][0] if kept else nr._seq
        for e in kept:
            nr._ring[e[0] % nr.size] = e
        return nr

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self.size
            self._seq = 0
            self._lo = 0

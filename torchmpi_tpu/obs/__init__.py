"""Runtime observability: collective telemetry registry + flight recorder.

Off by default and **never imported when off** — the same discipline as
``torchmpi_tpu.analysis``: every call site in the library guards its
``obs`` hook behind one ``Config.obs != "off"`` branch, so a build that
never opts in pays one string compare per collective dispatch and zero
import cost.  Enable via ``Config.obs`` / ``TORCHMPI_TPU_OBS``:

- ``"metrics"`` — the :class:`~torchmpi_tpu.obs.registry.Registry`
  accumulates counters and log2-bucketed histograms (per-op launch and
  byte counts keyed by op/dtype/size-bucket/backend/mesh, fusion
  coalescing stats, gradient-sync rounds, ZeRO legs, tuning plan
  hits/misses and measured medians, parameter-server cycle counters),
  and the :class:`~torchmpi_tpu.obs.recorder.FlightRecorder` ring
  buffers the last N collective events appended *before* dispatch —
  the post-mortem for runtime deadlocks (``scripts/obs_tool.py blame``
  aligns per-host dumps and names the first diverging collective, the
  runtime complement to the static analyzer's D1/D3 rules).  Both are
  dumped per host as JSONL (renderable as Prometheus text) at exit, on
  SIGTERM, or via :func:`dump`.
- ``"trace"`` — metrics plus per-event *user call-site attribution*
  (a stack walk per eager dispatch — the one genuinely costly hook, so
  it is gated behind the louder mode).

See docs/OBSERVABILITY.md for the metric catalog and workflows.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import threading
import time
from typing import List, Optional

from .recorder import FlightRecorder
from .registry import Registry, log2_bucket, prometheus_lines

MODES = ("off", "metrics", "trace")

DEFAULT_OUT_DIR = "/tmp/torchmpi_tpu_obs"
DEFAULT_RING = 1024

_lock = threading.Lock()
_mode = "off"
_out_dir = DEFAULT_OUT_DIR
_host = str(os.getpid())
_registry = Registry()
_recorder = FlightRecorder(DEFAULT_RING)
_atexit_armed = False
# Previous SIGTERM disposition while our handler is installed.  The
# sentinel is NOT None: signal.signal() legitimately returns None when
# the prior handler was installed from C, and that case must still
# terminate (treated like SIG_DFL) rather than read as "not installed".
_UNINSTALLED = object()
_prev_sigterm = _UNINSTALLED


def mode() -> str:
    return _mode


def active() -> bool:
    return _mode != "off"


def tracing() -> bool:
    return _mode == "trace"


def registry() -> Registry:
    return _registry


def recorder() -> FlightRecorder:
    return _recorder


def mesh_label(mesh) -> str:
    """``axis:size`` signature of a mesh (duck-typed — no jax import
    here), matching ``tuning.fingerprint.mesh_key``."""
    try:
        return ",".join(f"{a}:{int(s)}" for a, s in mesh.shape.items())
    except Exception:  # noqa: BLE001 — a label must never fail a step
        return "unknown"


# ---------------------------------------------------------------------------
# Activation (runtime.init / set_config call this when Config.obs is on)
# ---------------------------------------------------------------------------


def activate(obs_mode: str, *, out_dir: Optional[str] = None,
             ring_size: Optional[int] = None,
             host: Optional[str] = None) -> None:
    """Turn telemetry on (idempotent; re-activation updates settings).

    Installs the atexit dump once per process and chains a SIGTERM
    handler (dump, then the previous disposition) so a preempted or
    timed-out job still leaves its per-host evidence behind.
    """
    global _mode, _out_dir, _host, _recorder
    if obs_mode not in ("metrics", "trace"):
        raise ValueError(f"obs mode must be metrics|trace, got {obs_mode!r}")
    with _lock:
        _mode = obs_mode
        if out_dir:
            _out_dir = out_dir
        if host is not None:
            _host = str(host)
        if ring_size is not None and int(ring_size) != _recorder.size:
            # Carry history + seq forward: a mid-run resize (e.g.
            # enlarging after blame reports trimmed rings) must not
            # destroy the evidence already collected.
            _recorder = _recorder.resized(int(ring_size))
    _arm_handlers()


def deactivate() -> None:
    """Stop recording; restores the pre-activation SIGTERM disposition.
    Accumulated data stays readable (and dumpable explicitly)."""
    global _mode, _prev_sigterm
    with _lock:
        _mode = "off"
        prev, _prev_sigterm = _prev_sigterm, _UNINSTALLED
    if prev is not _UNINSTALLED:
        try:
            # A C-installed prior handler (None) cannot be restored
            # from Python; SIG_DFL at least keeps TERM terminating.
            signal.signal(signal.SIGTERM,
                          prev if prev is not None else signal.SIG_DFL)
        except (ValueError, OSError):  # non-main thread / teardown
            pass


def reset() -> None:
    """Clear all accumulated telemetry (tests)."""
    _registry.clear()
    _recorder.clear()


def _arm_handlers() -> None:
    global _atexit_armed, _prev_sigterm
    if not _atexit_armed:
        _atexit_armed = True
        atexit.register(_atexit_dump)
    if _prev_sigterm is _UNINSTALLED:
        try:
            prev = signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            return  # signals only work in the main thread
        # Re-activation after our handler is already installed must not
        # chain to itself.
        _prev_sigterm = prev if prev is not _on_sigterm else signal.SIG_DFL


def _atexit_dump() -> None:
    if active():
        try:
            # best_effort: this also runs from the SIGTERM handler on
            # the main thread — a blocking acquire against a lock held
            # by the interrupted frame would self-deadlock the dump.
            dump(best_effort=True)
        except Exception:  # noqa: BLE001 — never mask the exit path
            pass


def _on_sigterm(signum, frame) -> None:
    _atexit_dump()
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
    elif prev == signal.SIG_DFL or prev is None or prev is _UNINSTALLED:
        # SIG_DFL, an unrestorable C-installed handler (None), or a
        # race with deactivate: preserve die-on-TERM semantics after
        # dumping — a polite kill must never be silently swallowed.
        try:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)
        except (ValueError, OSError):
            raise SystemExit(128 + signum)


# ---------------------------------------------------------------------------
# Dump (JSONL per host; Prometheus text on request)
# ---------------------------------------------------------------------------


def metrics_path(out_dir: Optional[str] = None) -> str:
    return os.path.join(out_dir or _out_dir, f"metrics_host{_host}.jsonl")


def flight_path(out_dir: Optional[str] = None) -> str:
    return os.path.join(out_dir or _out_dir, f"flight_host{_host}.jsonl")


def _meta(stream: str) -> dict:
    return {"kind": "meta", "stream": stream, "host": _host,
            "pid": os.getpid(), "mode": _mode, "time": time.time()}


def dump(out_dir: Optional[str] = None,
         prom_path: Optional[str] = None,
         best_effort: bool = False) -> List[str]:
    """Write this process's telemetry snapshot; returns paths written.

    Overwrites (snapshot semantics): each dump is the complete
    cumulative state, so the file left by SIGTERM/atexit is always
    whole.  ``prom_path`` additionally renders the metrics snapshot in
    Prometheus text format.  ``best_effort`` bounds the lock acquires
    (the signal-handler path — see ``Registry.snapshot``).
    """
    base = out_dir or _out_dir
    os.makedirs(base, exist_ok=True)
    written: List[str] = []
    snap = _registry.snapshot(best_effort)
    mpath = metrics_path(base)
    with open(mpath, "w") as f:
        for rec in [_meta("metrics")] + snap:
            f.write(json.dumps(rec) + "\n")
    written.append(mpath)
    fmeta = _meta("flight")
    fmeta.update({"ring": _recorder.size, "total": _recorder.total,
                  "dropped": _recorder.dropped})
    fpath = flight_path(base)
    with open(fpath, "w") as f:
        for rec in [fmeta] + _recorder.to_records(best_effort):
            f.write(json.dumps(rec) + "\n")
    written.append(fpath)
    if prom_path:
        with open(prom_path, "w") as f:
            f.write("\n".join(prometheus_lines(snap)) + "\n")
        written.append(prom_path)
    return written


# ---------------------------------------------------------------------------
# Call-site hooks.  Every caller gates on ``Config.obs != "off"`` before
# importing this module, so these can assume telemetry is wanted; they
# must still never raise into a training step.
# ---------------------------------------------------------------------------


def _call_site() -> str:
    """Best-effort user call site (``file.py:line``): the first stack
    frame outside this package AND outside installed libraries (the
    eager verbs dispatch through ``jax.tree.map``, so jax frames sit
    between us and the user) — trace-mode only (a stack walk per
    dispatch is the one hook too costly for the metrics tier)."""
    import traceback

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for fr in reversed(traceback.extract_stack()[:-2]):
        fn = os.path.abspath(fr.filename)
        if fn.startswith(pkg) or "site-packages" in fn \
                or "dist-packages" in fn:
            continue
        return f"{fr.filename}:{fr.lineno}"
    return ""


def record_eager(op: str, nbytes: int, backend: str, mesh,
                 dtype=None) -> None:
    """One eager rank-major collective dispatch (the runtime hot path:
    counter + byte histogram + flight event; trace mode adds the user
    call site to the event)."""
    mk = mesh_label(mesh)
    labels = dict(op=op, backend=backend, mesh=mk,
                  dtype=str(dtype) if dtype is not None else "",
                  nbytes_bucket=f"b{log2_bucket(nbytes)}")
    _registry.counter_inc("tm_collectives_total", **labels)
    _registry.counter_inc("tm_collective_bytes_total", nbytes, **labels)
    _registry.hist_observe("tm_collective_nbytes", nbytes,
                           op=op, backend=backend, mesh=mk)
    detail = f"{mk} @{_call_site()}" if _mode == "trace" else mk
    _recorder.append("eager", op, nbytes, backend, detail)


def eager_recorder(op: str, nbytes: int, backend: str, mesh, dtype):
    """Pre-bound per-dispatch recorder for one eager CollectivePlan
    (torchmpi_tpu/planner.py): label-equivalent to :func:`record_eager`
    but with the label keys resolved ONCE at plan build, so the
    replay-path cost is three pre-keyed registry updates plus the
    flight-ring append.  The recorder reads the module-level mode/ring
    per call, so trace-mode attribution and ring resizes stay live."""
    mk = mesh_label(mesh)
    labels = dict(op=op, backend=backend, mesh=mk,
                  dtype=str(dtype) if dtype is not None else "",
                  nbytes_bucket=f"b{log2_bucket(nbytes)}")
    inc_calls = _registry.counter_handle("tm_collectives_total", **labels)
    inc_bytes = _registry.counter_handle("tm_collective_bytes_total",
                                         **labels)
    obs_bytes = _registry.hist_handle("tm_collective_nbytes", op=op,
                                      backend=backend, mesh=mk)

    def record() -> None:
        inc_calls()
        inc_bytes(nbytes)
        obs_bytes(nbytes)
        detail = f"{mk} @{_call_site()}" if _mode == "trace" else mk
        _recorder.append("eager", op, nbytes, backend, detail)

    return record


def record_eager_done(op: str, nbytes: int, backend: str, mesh) -> None:
    """The COMPLETION edge of one eager dispatch (ring only — launch
    counters already counted it at the dispatch edge).  Pairing both
    edges is what lets ``obs_tool blame`` distinguish "launched and
    stuck inside it" (a dispatch with no matching ``eager_done``) from
    "launched and done, the next one never launched"
    (docs/WATCHDOG.md's live-blame workflow)."""
    _recorder.append("eager_done", op, nbytes, backend, mesh_label(mesh))


def eager_done_recorder(op: str, nbytes: int, backend: str, mesh):
    """Pre-bound completion recorder for one eager CollectivePlan (the
    :func:`eager_recorder` companion): labels resolved once at build,
    the replay pays one ring append."""
    mk = mesh_label(mesh)

    def record_done() -> None:
        _recorder.append("eager_done", op, nbytes, backend, mk)

    return record_done


def record_watchdog(action: str, site: str, *, op: str = "",
                    seq: int = -1, elapsed_s: float = 0.0,
                    peer: str = "") -> None:
    """One ``torchmpi_tpu.watchdog`` event (docs/WATCHDOG.md):
    ``action`` is ``armed`` (an instrumented wait opened its in-flight
    window) | ``stalled`` (a window outlived ``watchdog_deadline_s``) |
    ``broken`` (break mode converted it into a typed
    ``CollectiveHangError``) | ``escalated`` (an unbreakable stall took
    the clean-exit ladder) | ``cleared`` (a flagged stall completed on
    its own — the genuinely-slow-collective signal deadline tuning
    reads) — counter ``tm_watchdog_<action>_total{site}``.  Everything
    past ``armed`` also rides the flight ring carrying op/seq/elapsed,
    so a post-mortem sees the stall verdict right next to the
    collective events it indicts."""
    labels = {"site": site}
    if peer:
        labels["peer"] = peer
    _registry.counter_inc(f"tm_watchdog_{action}_total", **labels)
    if action != "armed":
        detail = f"{action} elapsed={elapsed_s:.3g}s"
        if peer:
            detail += f" peer={peer}"
        _recorder.append("watchdog", op or site, max(0, int(seq)), site,
                         detail)


def record_plan(event: str, op: str, kind: str = "",
                build_s: Optional[float] = None) -> None:
    """One CollectivePlan table event (docs/PLANNER.md): ``event`` is
    ``hit`` | ``miss`` (counter ``tm_plan_<event>_total``, labeled by
    op and plan kind).  A miss — a plan build — also lands its build
    latency on the ``tm_plan_build_seconds`` histogram and a ``plan``
    flight-ring event, so post-mortems can see re-planning churn right
    next to the collectives it delayed.  (Steady-state hits are counted
    through per-plan pre-bound handles; this function is the build-side
    and tooling entry point.)"""
    _registry.counter_inc(f"tm_plan_{event}_total", op=op, kind=kind)
    if build_s is not None:
        _registry.hist_observe("tm_plan_build_seconds", build_s, op=op)
    if event == "miss":
        _recorder.append("plan", op, 0, kind, "build")


def record_in_axis(op: str, nbytes: int, axes) -> None:
    """One in-axis collective call (trace-time: counts program builds,
    not steady-state executions — jit replays don't re-enter)."""
    _registry.counter_inc("tm_inaxis_calls_total", op=op,
                          axes=",".join(map(str, axes)),
                          nbytes_bucket=f"b{log2_bucket(nbytes)}")


def record_fusion(op: str, n_leaves: int, n_launches: int,
                  wire_bytes: int, saved_bytes: int) -> None:
    """One ``fusion.fuse_tree`` coalescing (trace-time)."""
    _registry.counter_inc("tm_fusion_trees_total", op=op)
    _registry.counter_inc("tm_fusion_leaves_total", n_leaves, op=op)
    _registry.counter_inc("tm_fusion_buckets_total", n_launches, op=op)
    _registry.counter_inc("tm_fusion_wire_bytes_total", wire_bytes, op=op)
    _registry.counter_inc("tm_fusion_bytes_saved_total", saved_bytes, op=op)


def record_gradsync(n_buckets: int, op: str, compress) -> None:
    """One ``synchronize_gradients`` round (trace-time).  ``compress``
    is the wire codec NAME ("bf16", "dcn-int8", ... — "none" when
    uncompressed), so dumps distinguish the legacy bf16 cast from the
    quantized DCN codecs; boolean spellings from older callers keep
    their meaning (True == the legacy bf16 wire)."""
    if isinstance(compress, bool):
        name = "bf16" if compress else "none"
    else:
        name = str(compress) if compress else "none"
    _registry.counter_inc("tm_gradsync_rounds_total", op=op,
                          compressed=name)
    _registry.counter_inc("tm_gradsync_buckets_total", max(1, n_buckets))


def record_zero(kind: str, n_groups: int, n_shards: int) -> None:
    """One ZeRO reduce-scatter leg set (trace-time)."""
    _registry.counter_inc("tm_zero_sync_rounds_total", kind=kind,
                          n_shards=str(n_shards))
    _registry.counter_inc("tm_zero_groups_total", n_groups, kind=kind)


def record_dcn(op: str, codec: str, wire_bytes: int,
               payload_bytes: int) -> None:
    """One inter-slice (DCN) leg of a two-level collective
    (trace-time; docs/HIERARCHICAL.md): ``wire_bytes`` is what one
    device actually puts on the DCN links (quantized payload + scale),
    ``payload_bytes`` the uncompressed shard it represents — the ratio
    is the codec's measured win, the counter
    ``collectives_bench.py --dcn-compare`` asserts on."""
    _registry.counter_inc("tm_dcn_legs_total", op=op, codec=codec)
    _registry.counter_inc("tm_dcn_wire_bytes_total", wire_bytes,
                          op=op, codec=codec)
    _registry.counter_inc("tm_dcn_payload_bytes_total", payload_bytes,
                          op=op, codec=codec)


def record_selector_fallback(op: str, backend: str) -> None:
    """One selector topology/availability degradation (a requested
    backend silently replaced by "xla" — e.g. "hierarchical" on an
    ``n_dcn <= 1`` mesh), so misconfigured topologies show up in dumps
    instead of only as a missing perf win."""
    _registry.counter_inc("tm_selector_fallback_total", op=op,
                          backend=backend)


def record_tuning_plan(event: str, op: str = "") -> None:
    """Plan consult outcome: ``hit`` | ``miss`` | ``measured``."""
    _registry.counter_inc("tm_tuning_plan_lookups_total", event=event,
                          op=op)


def record_tuning_measure(op: str, backend: str, median_s: float) -> None:
    """One measured candidate (``tuning.measure`` result), median in
    microseconds on a log2 histogram."""
    _registry.hist_observe("tm_tuning_measured_us",
                           max(1.0, median_s * 1e6), op=op, backend=backend)


def record_ps_wait(n_futures: int) -> None:
    """The completion edge of one parameter-server wait (every shard
    future resolved) — ring only, the shard-level counters ride
    :func:`record_ps_stats`.  A gang wedged inside a PS wait shows the
    preceding dispatch as its last event; one that cleared it shows
    this."""
    _recorder.append("ps_wait_done", "ps", int(n_futures))


def record_ps_stats(stats: dict, prev: Optional[dict]) -> None:
    """Fold a ``ShardedParameterServer.stats()`` snapshot into the
    registry as deltas against the previous snapshot (the native
    counters are cumulative; the registry re-exports them as
    monotonic ``tm_ps_*`` counters)."""
    prev = prev or {}
    for k, v in stats.items():
        d = v - prev.get(k, 0)
        if d > 0:
            _registry.counter_inc(f"tm_ps_{k}_total", d)


def record_step_build(label: str) -> None:
    """One step-builder compilation-cache entry (trace-time)."""
    _registry.counter_inc("tm_step_builds_total", label=label)


def record_step(site: str, step: int = -1) -> None:
    """One step boundary (ring only — one append per step, no counter):
    ``data_parallel_step`` marks each dispatch, ``guard.run_guarded``
    each guarded iteration, the serving scheduler each tick.
    Consecutive ``step`` events delimit the attribution windows
    ``obs_tool attribute`` budgets (docs/OBSERVABILITY.md "Attribution
    workflow"); the step index rides the nbytes slot so blame's
    cross-host alignment keys on it."""
    _recorder.append("step", site, max(0, int(step)))


def record_log(logger_name: str) -> None:
    """One ``utils.metrics.MetricsLogger`` record (the logger is a thin
    wrapper over this registry when obs is active)."""
    _registry.counter_inc("tm_log_records_total", logger=logger_name)


def record_barrier(name: str) -> None:
    """A runtime barrier (barrier events anchor cross-host alignment in
    ``obs_tool.py blame``)."""
    _registry.counter_inc("tm_barriers_total")
    _recorder.append("barrier", name)


def record_barrier_done(name: str) -> None:
    """The barrier's completion edge (ring only) — without it blame
    cannot tell a host stuck INSIDE the barrier from one that cleared
    it and hung before its next dispatch."""
    _recorder.append("barrier_done", name)


def record_fault(action: str, site: str, *, kind: str = "",
                 peer: str = "") -> None:
    """One ``torchmpi_tpu.faults`` event: ``action`` is ``injected`` |
    ``retry`` | ``survived`` | ``exhausted`` | ``deadline`` | ``health``
    (counter ``tm_fault_<action>_total``).  Injected and
    deadline/health events also land in the flight ring, so
    ``obs_tool.py blame`` can name the injected site right next to the
    collective it wounded (docs/FAULTS.md)."""
    labels = {"site": site}
    if kind:
        labels["kind"] = kind
    if peer:
        labels["peer"] = peer
    _registry.counter_inc(f"tm_fault_{action}_total", **labels)
    if action in ("injected", "deadline", "health"):
        _recorder.append("fault", site, 0, kind, action)


def record_guard(action: str, site: str, *, peer: str = "",
                 digest: str = "", nbytes: int = 0) -> None:
    """One ``torchmpi_tpu.guard`` event (docs/GUARD.md): ``action`` is
    ``verified`` | ``verify_failed`` | ``healed`` | ``numeric_tripped``
    | ``skipped_step`` | ``rewind`` | ``quarantined`` (counter
    ``tm_guard_<action>_total{site,peer}``).  Wire verifies land in the
    flight ring with the payload digest in the backend slot, so
    ``obs_tool blame`` — which compares ``(ev, op, nbytes, backend)``
    per seq across hosts — names the first rank whose digest diverged
    from the gang's; failures/heals/rewinds always ride the ring as
    post-mortem anchors."""
    labels = {"site": site}
    if peer:
        labels["peer"] = peer
    _registry.counter_inc(f"tm_guard_{action}_total", **labels)
    if action in ("verified", "verify_failed", "healed",
                  "numeric_tripped", "rewind", "quarantined"):
        _recorder.append("guard", site, int(nbytes), digest[:12], action)


def record_guard_latency(site: str, seconds: float) -> None:
    """One wire-integrity digest verification: per-site latency in
    MICROSECONDS (``tm_guard_verify_us{site}``; the
    ``tm_tuning_measured_us`` convention so log2 buckets resolve
    sub-millisecond hashes) — the measured cost model docs/GUARD.md
    quotes per payload size."""
    _registry.hist_observe("tm_guard_verify_us",
                           max(1.0, float(seconds) * 1e6), site=site)


def record_async(event: str, op: str, *, wait_s: Optional[float] = None,
                 nbytes: int = 0) -> None:
    """One :class:`~torchmpi_tpu.collectives.AsyncHandle` lifecycle
    event: ``event`` is ``create`` | ``wait``.  ``wait_s`` lands on the
    ``tm_async_wait_seconds`` histogram — ONE observation per blocking
    call (``wait_all`` records its batch elapsed once under
    ``op="wait_all"``, never once per handle), so sum/count give the
    exact mean time blocked per call.  All events land in the flight
    ring, so a gang wedged inside a handle wait shows the handle as
    its last event."""
    _registry.counter_inc("tm_async_handles_total", event=event, op=op)
    if wait_s is not None:
        _registry.hist_observe("tm_async_wait_seconds", wait_s, op=op)
    _recorder.append("async", op, int(nbytes), "", event)


def record_overlap(stage: str, bucket: int, total: int) -> None:
    """One overlapped-gradsync schedule event, fired at RUNTIME from a
    debug callback inside the backward pass (docs/OVERLAP.md):
    ``stage`` is ``grads`` (bucket ``bucket``'s cotangents just
    materialized) or ``launch`` (its allreduce is being handed to the
    scheduler).  The flight-ring interleaving of these events is the
    CPU-sim-checkable overlap invariant — bucket *i*'s ``launch``
    recorded before bucket *i+1*'s ``grads`` — that
    ``benchmarks/overlap_trace.py`` and the gradsync tests assert."""
    _registry.counter_inc("tm_overlap_events_total", stage=stage)
    _recorder.append("overlap", stage, int(bucket), "",
                     f"bucket {bucket}/{total}")


def record_serving(event: str, n: int = 1, *, replica: str = "") -> None:
    """One serving-layer counter event (docs/SERVING.md): ``event`` is
    ``requests`` (admitted) | ``completed`` | ``tokens`` (emitted) |
    ``rerouted`` (sessions moved off a dead replica) | ``rejected``
    (unservable request refused at admission) | ``readmitted`` (a
    healed replica returned to the dispatch rotation) |
    ``prefill_compiles`` (a prompt length the engine had not prefilled
    before — one new XLA specialization; O(buckets) with bucketed
    prefill, O(distinct lengths) without) | ``spec_drafted`` /
    ``spec_accepted`` (speculative-decode draft tokens proposed /
    accepted — the live acceptance rate) | ``prefix_hits`` /
    ``prefix_misses`` / ``prefix_tokens_saved`` /
    ``prefix_bytes_saved`` / ``prefix_inserted`` / ``prefix_evicted``
    (radix prefix-cache admissions: blocks reused, prefill tokens and
    cache bytes not recomputed, tree churn) | ``admitted`` / ``shed``
    (the SLO admission gate's verdict per arrival) | ``scale_up`` /
    ``scale_down`` (FleetController replica-count changes) — counter
    ``tm_serving_<event>_total`` labeled by replica.  Re-routes also
    land in the flight ring, so a post-mortem sees the replica death
    next to the collectives (or faults) that preceded it."""
    _registry.counter_inc(f"tm_serving_{event}_total", n, replica=replica)
    if event == "rerouted":
        _recorder.append("serving", event, int(n), "", replica)


def record_serving_latency(kind: str, seconds: float, *,
                           replica: str = "") -> None:
    """One per-request SLO observation: ``kind`` is ``ttft``
    (time-to-first-token) or ``itl`` (inter-token latency) — histogram
    ``tm_serving_<kind>_us`` in MICROSECONDS, so the log2 buckets
    resolve sub-second latencies (the ``tm_tuning_measured_us``
    convention); ``obs_tool slo`` renders p50/p95/p99 per replica."""
    _registry.hist_observe(f"tm_serving_{kind}_us",
                           max(1.0, float(seconds) * 1e6),
                           replica=replica)


def record_serving_depth(depth: int) -> None:
    """Admission-queue depth, sampled once per scheduler tick (a gauge
    exposed as a histogram: count = ticks, sum/count = mean depth)."""
    _registry.hist_observe("tm_serving_queue_depth", max(0, int(depth)))


def record_serving_occupancy(pct: float, *, replica: str = "") -> None:
    """Slot-block occupancy percent per replica, sampled per tick."""
    _registry.hist_observe("tm_serving_slot_occupancy_pct",
                           max(0.0, float(pct)), replica=replica)


def record_restart(event: str, step: int) -> None:
    """One checkpoint-restart driver event (``utils/restart.py``):
    ``recovered`` (settled on a checkpoint step), ``fresh_start`` (no
    common restorable step), or ``peer_timeout`` (a detected-dead peer
    routed through the restore path)."""
    _registry.counter_inc("tm_restart_events_total", event=event)
    _recorder.append("restart", event, int(step))


def record_ckpt(event: str, *, step: int = 0, reason: str = "") -> None:
    """One durable-checkpoint event (``utils/checkpoint.py`` +
    ``utils/durable.py`` — docs/CHECKPOINT.md): ``event`` is ``saved``
    (a digest-stamped pair + its buddy mirrors committed) |
    ``verified`` (a restore's digest check passed) | ``verify_failed``
    (a copy failed it — ``reason`` names primary vs ``buddy_r<k>``) |
    ``repaired`` (the primary was rewritten bit-identically from the
    buddy named by ``reason``) | ``pruned`` (retention removed a
    step) | ``walkback`` (recovery rejected a step — ``reason`` is
    corrupt | missing | template_mismatch) — counter
    ``tm_ckpt_<event>_total``.  Every event rides the flight ring with
    the STEP in the nbytes slot, so ``obs_tool`` post-mortems can
    attribute which step recovery settled on and why the steps above
    it were rejected, aligned against the collectives around them."""
    labels = {"reason": reason} if reason else {}
    _registry.counter_inc(f"tm_ckpt_{event}_total", **labels)
    _recorder.append("ckpt", event, int(step), reason, event)


def record_elastic(event: str, *, epoch: int = 0, members: int = 0,
                   peer: str = "") -> None:
    """One elastic gang-resize event (``torchmpi_tpu/elastic.py`` —
    docs/ELASTIC.md): ``event`` is ``reconcile`` (a membership view
    committed) | ``shrink`` (the gang re-formed without a dead member)
    | ``rejoin`` (a healed member re-admitted at a step boundary) |
    ``quorum_lost`` (a reconcile/agreement refused on a minority side
    of a partition) | ``parked`` (the rank entered the quorum park
    loop) | ``fenced`` (a stale-epoch write was refused by the epoch
    fence) | ``healed`` (a parked rank rejoined a committed epoch) —
    counter ``tm_elastic_<event>_total``, labeled with the implicated
    member(s) when there are any.  Every event also lands in the
    flight ring, so a post-mortem sees the resize right next to the
    last collectives of the old gang."""
    labels = {}
    if peer:
        labels["peer"] = peer
    _registry.counter_inc(f"tm_elastic_{event}_total", **labels)
    _recorder.append("elastic", event, int(members), "",
                     f"epoch {int(epoch)}")


def record_hotstate(event: str, *, step: int = 0, peer: str = "",
                    reason: str = "") -> None:
    """One hot-state replication-tier event (``torchmpi_tpu/hotstate``
    — docs/HOTSTATE.md): ``event`` is ``streamed`` (a rank shipped its
    post-step delta/snapshot to its buddy's RAM — ``reason`` is
    ``snap`` | ``delta``) | ``received`` (the buddy landed it) |
    ``dropped`` (an injected ``hotstate.send``/``hotstate.recv`` fault
    ate the message — the chain self-heals at the next snapshot) |
    ``restored`` (the RAM rung reconstructed a digest-verified state) |
    ``verify_failed`` (a candidate replica failed its blake2b check —
    ``reason`` is ``digest`` or the parse error class) |
    ``fallback_disk`` (the ladder stepped down to the disk buddies) |
    ``evicted`` (the memory budget trimmed an old generation) |
    ``peer_lost`` (a streaming peer left the gang; its replicas stay) |
    ``migrated`` (a live drain landed a rank on a spare) — counter
    ``tm_hotstate_<event>_total``.  Every event rides the flight ring
    with the STEP in the nbytes slot, so a post-mortem sees which rung
    recovery actually took right next to the collectives around it."""
    labels = {}
    if peer:
        labels["peer"] = peer
    if reason:
        labels["reason"] = reason
    _registry.counter_inc(f"tm_hotstate_{event}_total", **labels)
    _recorder.append("hotstate", event, int(step), peer, reason or event)

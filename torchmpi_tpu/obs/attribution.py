"""Step-time attribution: flight ring + histograms -> per-step budget.

Turns one host's flight-recorder dump (the ``eager``/``barrier``
dispatch edges, their PR 14 completion edges ``eager_done`` /
``barrier_done`` / ``ps_wait_done``, the ``plan``/``guard`` anchors and
the ``step`` boundary events) plus the ``tm_*`` histogram snapshot into
a per-step time budget whose phase shares sum to the step wall time:

- ``collective_wait`` — paired dispatch->completion intervals of eager
  collectives (non-host backends), barrier spans, and PS waits;
- ``host_staging``  — the same pairing for host-staged backends (the
  D2H/allreduce/H2D round-trip runs on the host clock);
- ``compile``       — ``plan`` ring events (cache misses) costed at the
  measured mean of ``tm_plan_build_seconds``;
- ``guard_verify``  — ``guard`` verify events costed at the measured
  mean of ``tm_guard_verify_us``;
- ``dispatch_gap``  — the residual: host time between dispatches where
  the device had nothing blocking (python, input pipeline, optimizer
  glue).

Windows come from the ``step`` boundary events recorded by
``data_parallel_step`` / ``run_guarded`` / the serving tick when
``Config.obs != "off"``; a ring with fewer than two markers degrades to
one whole-ring window (noted in the budget).  Overlapping intervals are
resolved by an endpoint sweep (no second is counted twice), and the two
histogram-costed phases are clamped into the uncovered remainder, so
the five phases sum to the window length *exactly* — the invariant
``tests/test_attribution.py`` asserts and CI's attribution-smoke job
checks on a real dump.

Deliberately stdlib-only and import-free within the package, so
``scripts/obs_tool.py attribute`` can load it by file path (the
``registry.py`` pattern) without importing jax.

Caveats inherited from the ring (docs/OBSERVABILITY.md): a direct
(in-graph) backend's ``eager_done`` marks the async *enqueue* return,
not device completion, so its "wait" is a lower bound; ``ps_wait_done``
has no dispatch edge and is costed from the previous event's timestamp;
a wrapped ring drops old dispatch edges, leaving completion edges
unpaired (costed like PS waits, counted in ``notes``).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

# Report order; also the sweep priority (host_staging beats
# collective_wait where intervals overlap: the more specific diagnosis
# wins the segment).
PHASES = ("dispatch_gap", "collective_wait", "host_staging",
          "compile", "guard_verify")

_SWEEP_PRIORITY = ("host_staging", "collective_wait")

# Guard ring events whose detail marks a wire-digest verification (the
# ones tm_guard_verify_us measured); rewind/quarantine anchors are
# bookkeeping, not per-step verify cost.
_GUARD_VERIFY_DETAILS = ("verified", "verify_failed", "healed")


def hist_mean(metrics: Sequence[dict], name: str,
              scale: float = 1.0) -> Optional[float]:
    """Mean of a registry histogram across every label set in a
    metrics snapshot (sum/count), scaled (e.g. 1e-6 for *_us series);
    None when the series never recorded."""
    tot = cnt = 0.0
    for rec in metrics or ():
        if rec.get("kind") == "hist" and rec.get("name") == name:
            tot += float(rec.get("sum", 0.0))
            cnt += float(rec.get("count", 0))
    return (tot / cnt) * scale if cnt else None


def _is_host_backend(backend: str) -> bool:
    return "host" in (backend or "")


def _pair_intervals(events: Sequence[dict]) -> Tuple[
        List[Tuple[float, float, str]], Dict[str, int]]:
    """FIFO-pair dispatch edges with their completion edges.

    Returns ``(intervals, stats)`` where each interval is
    ``(t0, t1, phase)``.  Completion edges whose dispatch edge fell off
    a wrapped ring are costed from the previous event's timestamp (the
    ``ps_wait_done`` rule); dispatch edges with no completion (still in
    flight, or a pre-PR-14 ring) contribute nothing but are counted.
    """
    intervals: List[Tuple[float, float, str]] = []
    open_eager: Dict[Tuple[str, int, str], deque] = {}
    open_barrier: Dict[str, deque] = {}
    unpaired_done = 0
    prev_ts: Optional[float] = None
    for ev in events:
        kind = ev.get("ev")
        ts = float(ev.get("ts", 0.0))
        if kind == "eager":
            key = (ev.get("op", ""), int(ev.get("nbytes", 0) or 0),
                   ev.get("backend", ""))
            open_eager.setdefault(key, deque()).append(ts)
        elif kind == "eager_done":
            key = (ev.get("op", ""), int(ev.get("nbytes", 0) or 0),
                   ev.get("backend", ""))
            q = open_eager.get(key)
            phase = ("host_staging"
                     if _is_host_backend(ev.get("backend", ""))
                     else "collective_wait")
            if q:
                intervals.append((q.popleft(), ts, phase))
            elif prev_ts is not None:
                unpaired_done += 1
                intervals.append((prev_ts, ts, phase))
        elif kind == "barrier":
            open_barrier.setdefault(ev.get("op", ""),
                                    deque()).append(ts)
        elif kind == "barrier_done":
            q = open_barrier.get(ev.get("op", ""))
            if q:
                intervals.append((q.popleft(), ts, "collective_wait"))
            elif prev_ts is not None:
                unpaired_done += 1
                intervals.append((prev_ts, ts, "collective_wait"))
        elif kind == "ps_wait_done" and prev_ts is not None:
            intervals.append((prev_ts, ts, "collective_wait"))
        prev_ts = ts
    unpaired_dispatch = (sum(len(q) for q in open_eager.values())
                         + sum(len(q) for q in open_barrier.values()))
    return intervals, {"unpaired_done": unpaired_done,
                       "unpaired_dispatch": unpaired_dispatch}


def _sweep_coverage(intervals: Sequence[Tuple[float, float, str]],
                    w0: float, w1: float) -> Dict[str, float]:
    """Per-phase covered seconds inside ``[w0, w1]`` with no segment
    counted twice: an endpoint sweep assigns each elementary segment to
    the highest-priority phase covering it."""
    clipped = [(max(t0, w0), min(t1, w1), phase)
               for t0, t1, phase in intervals
               if min(t1, w1) > max(t0, w0)]
    covered = {p: 0.0 for p in _SWEEP_PRIORITY}
    if not clipped:
        return covered
    points = sorted({t for t0, t1, _ in clipped for t in (t0, t1)})
    for a, b in zip(points, points[1:]):
        mid = (a + b) / 2.0
        for phase in _SWEEP_PRIORITY:
            if any(t0 <= mid < t1 for t0, t1, p in clipped
                   if p == phase):
                covered[phase] += b - a
                break
    return covered


def attribute_host(flight: Sequence[dict],
                   metrics: Optional[Sequence[dict]] = None,
                   host: str = "") -> Optional[dict]:
    """One host's per-step time budget (see module docstring).

    ``flight`` / ``metrics`` are the JSONL record lists of the host's
    dump pair (``kind`` meta lines tolerated and skipped).  Returns
    None for a flight stream with no events.
    """
    events = sorted((r for r in flight if r.get("ev")),
                    key=lambda r: int(r.get("seq", 0)))
    if not events:
        return None
    notes: List[str] = []
    step_ts = [float(e.get("ts", 0.0)) for e in events
               if e.get("ev") == "step"]
    if len(step_ts) >= 2:
        windows = list(zip(step_ts, step_ts[1:]))
    else:
        windows = [(float(events[0].get("ts", 0.0)),
                    float(events[-1].get("ts", 0.0)))]
        notes.append("no step markers; whole-ring window")

    intervals, pair_stats = _pair_intervals(events)
    if pair_stats["unpaired_done"]:
        notes.append(f"{pair_stats['unpaired_done']} completion edge(s) "
                     "lost their dispatch edge (wrapped ring); costed "
                     "from the previous event")
    if pair_stats["unpaired_dispatch"]:
        notes.append(f"{pair_stats['unpaired_dispatch']} dispatch(es) "
                     "never completed in-ring (in flight or wedged)")

    plan_mean = hist_mean(metrics or (), "tm_plan_build_seconds")
    guard_mean = hist_mean(metrics or (), "tm_guard_verify_us", 1e-6)

    totals = {p: 0.0 for p in PHASES}
    wall = 0.0
    clamped = False
    for w0, w1 in windows:
        span = w1 - w0
        if span <= 0:
            continue
        wall += span
        covered = _sweep_coverage(intervals, w0, w1)
        n_plan = sum(1 for e in events if e.get("ev") == "plan"
                     and w0 <= float(e.get("ts", 0.0)) < w1)
        n_guard = sum(1 for e in events if e.get("ev") == "guard"
                      and e.get("detail") in _GUARD_VERIFY_DETAILS
                      and w0 <= float(e.get("ts", 0.0)) < w1)
        compile_s = n_plan * (plan_mean or 0.0)
        guard_s = n_guard * (guard_mean or 0.0)
        if n_plan and plan_mean is None:
            notes.append("plan events without tm_plan_build_seconds; "
                         "compile share under-counted")
        if n_guard and guard_mean is None:
            notes.append("guard events without tm_guard_verify_us; "
                         "guard share under-counted")
        avail = max(0.0, span - sum(covered.values()))
        synth = compile_s + guard_s
        if synth > avail and synth > 0:
            scale = avail / synth
            compile_s *= scale
            guard_s *= scale
            clamped = True
        totals["collective_wait"] += covered["collective_wait"]
        totals["host_staging"] += covered["host_staging"]
        totals["compile"] += compile_s
        totals["guard_verify"] += guard_s
        totals["dispatch_gap"] += max(
            0.0, span - sum(covered.values()) - compile_s - guard_s)
    if clamped:
        notes.append("histogram-costed phases clamped into the "
                     "uncovered remainder")
    if wall <= 0:
        notes.append("zero-length window; shares undefined")
    n_steps = max(1, len(step_ts) - 1) if len(step_ts) >= 2 else 1
    return {
        "host": host,
        "steps": n_steps,
        "events": len(events),
        "wall_s": wall,
        "step_ms": (wall / n_steps) * 1e3,
        "phases": {p: {"seconds": totals[p],
                       "share": (totals[p] / wall) if wall > 0 else 0.0}
                   for p in PHASES},
        "notes": notes,
    }


def aggregate_shares(budgets: Sequence[dict]) -> Dict[str, float]:
    """Wall-time-weighted phase shares across hosts (seconds-summing,
    so a long host counts for its length, not one vote)."""
    wall = sum(b["wall_s"] for b in budgets)
    out = {}
    for p in PHASES:
        secs = sum(b["phases"][p]["seconds"] for b in budgets)
        out[p] = (secs / wall) if wall > 0 else 0.0
    return out


def diff_budgets(before: Sequence[dict],
                 after: Sequence[dict]) -> dict:
    """Name the phase whose share regressed between two dumps.

    Shares (not raw seconds) are compared so a run with more steps is
    not 'regressed' merely for being longer; the verdict is the phase
    with the largest share increase.
    """
    b = aggregate_shares(before)
    a = aggregate_shares(after)
    deltas = {p: a[p] - b[p] for p in PHASES}
    regressed = max(PHASES, key=lambda p: deltas[p])
    step_b = (sum(x["wall_s"] for x in before)
              / max(1, sum(x["steps"] for x in before)))
    step_a = (sum(x["wall_s"] for x in after)
              / max(1, sum(x["steps"] for x in after)))
    return {
        "regressed": regressed if deltas[regressed] > 0 else None,
        "deltas": deltas,
        "before": {"shares": b, "step_s": step_b},
        "after": {"shares": a, "step_s": step_a},
        "step_ratio": (step_a / step_b) if step_b > 0 else None,
    }


def format_table(budgets: Sequence[dict]) -> str:
    """Fixed-width per-host table (the ``obs_tool attribute`` default
    rendering)."""
    head = (["host", "steps", "ms/step"]
            + [p for p in PHASES] + ["notes"])
    rows = [head]
    for b in budgets:
        rows.append(
            [str(b["host"]), str(b["steps"]), f"{b['step_ms']:.2f}"]
            + [f"{b['phases'][p]['share'] * 100:5.1f}%" for p in PHASES]
            + ["; ".join(b["notes"]) if b["notes"] else "-"])
    widths = [max(len(r[i]) for r in rows) for i in range(len(head))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in rows]
    lines.insert(1, "  ".join("-" * w for w in widths))
    return "\n".join(lines)
